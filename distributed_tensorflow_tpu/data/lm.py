"""Causal-LM data streams for the GPT-mini workload: real text corpus or
synthetic.

Mirrors the reference's data-loader contract (``read_data_sets(data_dir)``
with a graceful source decision, reference ``distributed.py:6,38``): when
``data_dir`` holds ``*.txt`` files they become the corpus — byte-level
(vocab 256) by default, so any text trains as-is, or subword-tokenized with
``tokenizer="bpe"`` (:mod:`.tokenizer`, trained on the corpus's train split
only) — split 90/5/5 into contiguous train/validation/test regions.
Otherwise streams fall back to deterministic position-dependent-bigram
sequences (:func:`..models.gpt.synthetic_lm_batch`) that a decoder can
actually learn, behind the reference's ``next_batch`` API.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass

import numpy as np


class LmStream:
    """Batch stream with ``next_batch``; each call advances the sample seed."""

    def __init__(self, cfg, seq_len: int, seed: int):
        self.cfg = cfg
        self.seq_len = seq_len
        self._seed0 = seed
        self._seed = seed

    def next_batch(self, batch_size: int) -> dict:
        from ..models.gpt import synthetic_lm_batch
        batch = synthetic_lm_batch(self._seed, batch_size, self.seq_len,
                                   self.cfg)
        self._seed += 1
        return batch

    def shard(self, index: int, count: int) -> "LmStream":
        """Disjoint per-process stream (multi-controller sharded feed)."""
        del count
        return LmStream(self.cfg, self.seq_len,
                        self._seed + (index + 1) * 1_000_003)

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        from ..models.gpt import synthetic_lm_batch
        return [synthetic_lm_batch(20_000_000 + self._seed0 + i,
                                   batch_size, self.seq_len, self.cfg)
                for i in range(num_batches)]


def _sample_windows(data: np.ndarray, rng: np.random.Generator,
                    batch_size: int, seq_len: int) -> dict:
    """Seeded random fixed-length windows over ``data`` — the one sampling
    body every corpus stream shares.  +1: the high bound is exclusive; the
    last valid start position ``len(data) - seq_len`` must remain drawable
    or the region's final byte would never appear in any batch."""
    starts = rng.integers(0, len(data) - seq_len + 1, size=batch_size)
    toks = np.stack([data[s:s + seq_len] for s in starts])
    return {"tokens": toks.astype(np.int32)}


class ByteLmStream:
    """Random fixed-length byte windows over a corpus region; same
    ``next_batch``/``fixed_batches`` API as :class:`LmStream`."""

    def __init__(self, data: np.ndarray, seq_len: int, seed: int):
        if len(data) <= seq_len:
            raise ValueError(f"corpus region of {len(data)} bytes is too "
                             f"short for seq_len={seq_len}")
        self.data = data
        self.seq_len = seq_len
        self._seed0 = seed
        self._seed = seed

    def _windows(self, rng: np.random.Generator, batch_size: int) -> dict:
        return _sample_windows(self.data, rng, batch_size, self.seq_len)

    def next_batch(self, batch_size: int) -> dict:
        batch = self._windows(np.random.default_rng(self._seed), batch_size)
        self._seed += 1
        return batch

    def shard(self, index: int, count: int) -> "ByteLmStream":
        """Disjoint per-process stream (multi-controller sharded feed)."""
        del count
        return ByteLmStream(self.data, self.seq_len,
                            self._seed + (index + 1) * 1_000_003)

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        return [self._windows(
                    np.random.default_rng(20_000_000 + self._seed0 + i),
                    batch_size)
                for i in range(num_batches)]


class CorpusFiles:
    """Logical concatenation of on-disk files with range reads — the
    random-access view a streaming corpus needs without loading anything."""

    def __init__(self, paths: list[str]):
        self.paths = list(paths)
        self.sizes = [os.path.getsize(p) for p in self.paths]
        self.offsets = np.concatenate([[0], np.cumsum(self.sizes)])
        self.total = int(self.offsets[-1])

    def read(self, start: int, length: int) -> np.ndarray:
        """Bytes ``[start, start+length)`` of the logical corpus (clamped to
        the end), spanning file boundaries as needed."""
        end = min(start + length, self.total)
        out = np.empty(max(end - start, 0), np.uint8)
        pos = start
        while pos < end:
            fi = int(np.searchsorted(self.offsets, pos, side="right")) - 1
            local = pos - int(self.offsets[fi])
            n = min(end - pos, self.sizes[fi] - local)
            with open(self.paths[fi], "rb") as fh:
                fh.seek(local)
                out[pos - start:pos - start + n] = np.frombuffer(
                    fh.read(n), np.uint8)
            pos += n
        return out


class StreamingByteLmStream:
    """Chunked random-window stream over a corpus REGION that never holds
    more than one chunk in memory — corpora larger than RAM train.

    The region ``[lo, hi)`` of the logical corpus is cut into fixed
    ``chunk_bytes`` chunks (read with a ``seq_len`` overlap so windows
    crossing a chunk boundary exist).  Per epoch the chunk order is a
    seeded permutation; within a chunk, ``next_batch`` draws seeded random
    windows until the chunk's token budget (its own length) is consumed,
    then the next chunk loads — one epoch ≈ one pass over the region's
    tokens.  Everything is a pure function of ``(seed, epoch, chunk,
    draw)``, which buys the two distribution properties:

    - ``shard(index, count)``: processes take disjoint chunk subsets
      (``chunk % count == index``) — a per-process disjoint window over the
      files, nothing read twice across the fleet;
    - ``cursor()``/``restore_cursor()``: resume is deterministic — a
      restored stream continues with exactly the batches that followed
      the saved cursor.  (The training loop samples the cursor from the
      live stream, which its prefetcher has advanced past the
      checkpointed step, so end-to-end resume skips up to prefetch-depth
      batches — see ``training/loop.py``.)

    ``encode`` (optional) maps raw chunk bytes to token ids at load time
    (the BPE path); window sampling runs over the encoded ids.
    """

    def __init__(self, files: CorpusFiles, lo: int, hi: int, seq_len: int,
                 seed: int, chunk_bytes: int = 64 << 20, encode=None,
                 shard_index: int = 0, shard_count: int = 1):
        if hi - lo <= seq_len:
            raise ValueError(f"corpus region of {hi - lo} bytes is too "
                             f"short for seq_len={seq_len}")
        self.files = files
        self.lo, self.hi = lo, hi
        self.seq_len = seq_len
        self.chunk_bytes = chunk_bytes
        self.encode = encode
        self._seed0 = seed
        self._shard = (shard_index, shard_count)
        self.num_chunks = max(1, -(-(hi - lo) // chunk_bytes))
        self._epoch = 0
        self._perm_pos = 0
        self._draw = 0
        self._budget = 0
        self._chunk_data = None

    # ------------------------------------------------------------ internals

    def _my_chunks(self, epoch: int) -> np.ndarray:
        index, count = self._shard
        mine = np.arange(self.num_chunks)[index::count] if count > 1 else \
            np.arange(self.num_chunks)
        if mine.size == 0:
            # More processes than chunks: wrap so every process streams
            # SOMETHING (coverage beats strict disjointness here).
            mine = np.asarray([index % self.num_chunks])
        perm = np.random.default_rng(
            (self._seed0, 11, epoch)).permutation(mine.size)
        return mine[perm]

    def _read_encoded(self, start: int, end: int) -> np.ndarray:
        """Read+encode ``[start, end)``; on a degenerate result (tiny tail
        remainder, or a highly compressible region whose ENCODED length
        fell under a window) widen the read backward geometrically until
        one window exists."""
        data = self.files.read(start, end - start)
        if self.encode is not None:
            data = self.encode(data)
        width = end - start
        while len(data) <= self.seq_len:
            if start <= self.lo:
                raise ValueError(
                    f"corpus region [{self.lo}, {self.hi}) encodes to "
                    f"{len(data)} tokens <= seq_len={self.seq_len}")
            width *= 2
            start = max(self.lo, end - width)
            data = self.files.read(start, end - start)
            if self.encode is not None:
                data = self.encode(data)
        return np.asarray(data)

    def _load_chunk(self) -> None:
        order = self._my_chunks(self._epoch)
        c = int(order[self._perm_pos % order.size])
        start = self.lo + c * self.chunk_bytes
        end = min(start + self.chunk_bytes + self.seq_len, self.hi)
        self._chunk_data = self._read_encoded(start, end)
        self._budget = len(self._chunk_data)

    def _advance(self) -> None:
        self._perm_pos += 1
        if self._perm_pos >= self._my_chunks(self._epoch).size:
            self._perm_pos = 0
            self._epoch += 1
        self._chunk_data = None
        self._draw = 0

    # ------------------------------------------------------------ stream API

    def next_batch(self, batch_size: int) -> dict:
        if self._chunk_data is None:
            self._load_chunk()
        rng = np.random.default_rng(
            (self._seed0, self._epoch, self._perm_pos, self._draw))
        batch = _sample_windows(self._chunk_data, rng, batch_size,
                                self.seq_len)
        self._draw += 1
        self._budget -= batch_size * self.seq_len
        if self._budget <= 0:
            self._advance()
        return batch

    def shard(self, index: int, count: int) -> "StreamingByteLmStream":
        """Disjoint per-process stream (multi-controller sharded feed)."""
        return StreamingByteLmStream(
            self.files, self.lo, self.hi, self.seq_len, self._seed0,
            chunk_bytes=self.chunk_bytes, encode=self.encode,
            shard_index=index, shard_count=count)

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        """Deterministic eval batches from the region's FIRST chunk (a
        bounded prefix — eval never walks the whole streaming corpus)."""
        end = min(self.lo + self.chunk_bytes + self.seq_len, self.hi)
        data = self._read_encoded(self.lo, end)
        return [_sample_windows(data,
                                np.random.default_rng((self._seed0, 13, i)),
                                batch_size, self.seq_len)
                for i in range(num_batches)]

    # ------------------------------------------------------------- resume

    def _geometry(self) -> list:
        # Everything the chunk ordering and window sampling depend on: a
        # cursor from a different fleet size / region / chunking must be
        # rejected, not silently reinterpreted over a different chunk set.
        return [self._seed0, self.lo, self.hi, self.seq_len,
                self.chunk_bytes, list(self._shard)]

    def cursor(self) -> dict:
        """Serializable position; feed to :meth:`restore_cursor` to resume
        the exact batch sequence."""
        return {"epoch": self._epoch, "perm_pos": self._perm_pos,
                "draw": self._draw, "budget": self._budget,
                "loaded": self._chunk_data is not None,
                "geometry": self._geometry()}

    def restore_cursor(self, cur: dict) -> bool:
        """Returns False (and restores nothing) for a cursor written under
        a different stream geometry."""
        if cur.get("geometry") != self._geometry():
            return False
        self._epoch = int(cur["epoch"])
        self._perm_pos = int(cur["perm_pos"])
        if cur.get("loaded", True):
            self._load_chunk()
            self._draw = int(cur["draw"])
            self._budget = int(cur["budget"])
        else:
            # Cursor taken right after a chunk advance: the next chunk was
            # never loaded — restoring its stale budget would advance twice.
            self._chunk_data = None
            self._draw = 0
        return True


def load_byte_corpus(data_dir: str | None) -> np.ndarray | None:
    """Concatenated bytes of ``<data_dir>/*.txt`` (sorted), or None.

    ``*.txt`` only, deliberately: ``--data_dir`` defaults to the MNIST
    directory, whose IDX files must not silently become an LM corpus.
    """
    if not data_dir or not os.path.isdir(data_dir):
        return None
    paths = sorted(glob.glob(os.path.join(data_dir, "*.txt")))
    if not paths:
        return None
    def read_bytes(path):
        with open(path, "rb") as fh:
            return np.frombuffer(fh.read(), np.uint8)

    return np.concatenate([read_bytes(p) for p in paths])


@dataclass
class LmDatasets:
    train: LmStream
    validation: LmStream
    test: LmStream
    synthetic: bool = True


#: corpora above this switch to the chunked streaming reader (override via
#: make_lm_datasets(stream_threshold_bytes=...) / --gpt_stream_corpus_mb)
STREAM_THRESHOLD_BYTES = 256 << 20
#: bytes of the train region the BPE tokenizer trains on when streaming
#: (the merge table converges on a few MB; the full corpus never loads)
BPE_SAMPLE_BYTES = 8 << 20


def _make_streaming_datasets(paths, seq_len, tokenizer, bpe_vocab,
                             tokenizer_path, chunk_bytes, data_dir):
    files = CorpusFiles(paths)
    n = files.total
    train_end, val_end = int(n * 0.9), int(n * 0.95)
    encode = None
    if tokenizer == "bpe":
        from .tokenizer import BpeTokenizer
        sample = files.read(0, min(train_end, BPE_SAMPLE_BYTES))
        tok = BpeTokenizer.train(sample, bpe_vocab)
        if tokenizer_path:
            tok.save(tokenizer_path)
        encode = tok.encode
        print(f"gpt bpe streaming corpus: {n:,} bytes from {data_dir}/*.txt "
              f"(vocab {tok.vocab_size} trained on a {len(sample):,}-byte "
              f"sample; chunks of {chunk_bytes:,} bytes encoded at load)")
    else:
        if tokenizer_path:
            from .tokenizer import BpeTokenizer
            BpeTokenizer([]).save(tokenizer_path)  # identity: ids = bytes
        print(f"gpt byte streaming corpus: {n:,} bytes from {data_dir}/*.txt "
              f"(train {train_end:,} / validation {val_end - train_end:,} / "
              f"test {n - val_end:,}; chunks of {chunk_bytes:,} bytes)")
    mk = lambda lo, hi, seed: StreamingByteLmStream(
        files, lo, hi, seq_len, seed, chunk_bytes=chunk_bytes, encode=encode)
    return LmDatasets(
        train=mk(0, train_end, 0),
        validation=mk(train_end, val_end, 7_000_000),
        test=mk(val_end, n, 8_000_000),
        synthetic=False,
    )


def make_lm_datasets(cfg, seq_len: int = 128,
                     data_dir: str | None = None,
                     tokenizer: str = "byte",
                     bpe_vocab: int = 512,
                     tokenizer_path: str | None = None,
                     stream_threshold_bytes: int = STREAM_THRESHOLD_BYTES,
                     stream_chunk_bytes: int = 64 << 20) -> LmDatasets:
    """``tokenizer``: "byte" (ids = bytes, vocab 256) or "bpe" (byte-level
    BPE trained on the train region up to ``bpe_vocab`` tokens — the model's
    vocab must be >= that).  ``tokenizer_path`` persists the trained merge
    table (and an identity table for "byte") so eval/generate can decode
    ids back to text; every process derives the identical vocabulary
    deterministically, no broadcast needed.

    Corpora whose on-disk size exceeds ``stream_threshold_bytes`` never
    load into RAM: they stream through :class:`StreamingByteLmStream`
    (chunked reads, sharded disjoint chunk sets, cursor resume).  The BPE
    tokenizer then trains on a bounded train-region sample."""
    if tokenizer not in ("byte", "bpe"):
        raise ValueError(f"tokenizer must be 'byte' or 'bpe', got {tokenizer!r}")
    if data_dir and os.path.isdir(data_dir):
        paths = sorted(glob.glob(os.path.join(data_dir, "*.txt")))
        total = sum(os.path.getsize(p) for p in paths)
        if paths and total > stream_threshold_bytes:
            return _make_streaming_datasets(
                paths, seq_len, tokenizer, bpe_vocab, tokenizer_path,
                stream_chunk_bytes, data_dir)
    corpus = load_byte_corpus(data_dir)
    if corpus is not None:
        n = len(corpus)
        train_end, val_end = int(n * 0.9), int(n * 0.95)
        # Every 90/5/5 region must fit at least one window; below that the
        # source decision stays graceful — warn and use the synthetic stream.
        min_bytes = int((seq_len + 1) / 0.05) + 1
        if n - val_end <= seq_len or val_end - train_end <= seq_len:
            print(f"WARNING: byte corpus under {data_dir} has {n:,} bytes; "
                  f"need > {min_bytes:,} for seq_len={seq_len} "
                  "(each 5% validation/test split must exceed one window) — "
                  "falling back to the synthetic stream")
            corpus = None
    if corpus is not None and tokenizer == "bpe":
        from .tokenizer import BpeTokenizer
        tok = BpeTokenizer.train(corpus[:train_end], bpe_vocab)
        regions = [corpus[:train_end], corpus[train_end:val_end],
                   corpus[val_end:]]
        ids = [tok.encode(r) for r in regions]
        if any(len(r) <= seq_len for r in ids[1:]):
            print(f"WARNING: BPE-encoded corpus regions "
                  f"{[len(r) for r in ids]} tokens; each validation/test "
                  f"region must exceed seq_len={seq_len} — falling back to "
                  "the synthetic stream")
            corpus = None
        else:
            if tokenizer_path:
                tok.save(tokenizer_path)
            n_ids = sum(len(r) for r in ids)
            print(f"gpt bpe corpus: {n:,} bytes -> {n_ids:,} tokens "
                  f"(vocab {tok.vocab_size}, {n / max(n_ids, 1):.2f} "
                  f"bytes/token) from {data_dir}/*.txt (train {len(ids[0]):,}"
                  f" / validation {len(ids[1]):,} / test {len(ids[2]):,})")
            return LmDatasets(
                train=ByteLmStream(ids[0], seq_len, seed=0),
                validation=ByteLmStream(ids[1], seq_len, seed=7_000_000),
                test=ByteLmStream(ids[2], seq_len, seed=8_000_000),
                synthetic=False,
            )
    if corpus is not None:
        if tokenizer_path:
            from .tokenizer import BpeTokenizer
            BpeTokenizer([]).save(tokenizer_path)  # identity: ids = bytes
        print(f"gpt byte corpus: {n:,} bytes from {data_dir}/*.txt "
              f"(train {train_end:,} / validation {val_end - train_end:,} / "
              f"test {n - val_end:,})")
        return LmDatasets(
            train=ByteLmStream(corpus[:train_end], seq_len, seed=0),
            validation=ByteLmStream(corpus[train_end:val_end], seq_len,
                                    seed=7_000_000),
            test=ByteLmStream(corpus[val_end:], seq_len, seed=8_000_000),
            synthetic=False,
        )
    return LmDatasets(
        train=LmStream(cfg, seq_len, seed=0),
        validation=LmStream(cfg, seq_len, seed=7_000_000),
        test=LmStream(cfg, seq_len, seed=8_000_000),
    )


def make_lm_eval_fn(apply_fn, batch_size: int = 32, num_batches: int = 4):
    """Next-token accuracy over fixed batches; matches the loop's
    ``eval_fn(state, split) -> float`` signature.

    ``apply_fn(params, tokens) -> logits`` (deterministic apply).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _acc(params, tokens):
        logits = apply_fn(params, tokens)
        pred = jnp.argmax(logits[:, :-1], -1)
        correct = (pred == tokens[:, 1:]).astype(jnp.float32)
        return correct.sum(), jnp.float32(correct.size)

    def evaluate(state, split) -> float:
        from ..parallel.sharding import multihost_replicated_put
        put = multihost_replicated_put(state.params)
        num, den = 0.0, 0.0
        for batch in split.fixed_batches(batch_size, num_batches):
            n, d = _acc(state.params, put(batch["tokens"]))
            num += float(n)
            den += float(d)
        return num / max(den, 1.0)

    return evaluate
