"""Synthetic causal-LM data streams for the GPT-mini workload.

Same shape as :mod:`.mlm`: no corpus ships in the image, so streams generate
deterministic position-dependent-bigram byte sequences
(:func:`..models.gpt.synthetic_lm_batch`) that a decoder can actually learn,
behind the reference's ``next_batch`` API.
"""

from __future__ import annotations

from dataclasses import dataclass


class LmStream:
    """Batch stream with ``next_batch``; each call advances the sample seed."""

    def __init__(self, cfg, seq_len: int, seed: int):
        self.cfg = cfg
        self.seq_len = seq_len
        self._seed0 = seed
        self._seed = seed

    def next_batch(self, batch_size: int) -> dict:
        from ..models.gpt import synthetic_lm_batch
        batch = synthetic_lm_batch(self._seed, batch_size, self.seq_len,
                                   self.cfg)
        self._seed += 1
        return batch

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        from ..models.gpt import synthetic_lm_batch
        return [synthetic_lm_batch(20_000_000 + self._seed0 + i,
                                   batch_size, self.seq_len, self.cfg)
                for i in range(num_batches)]


@dataclass
class LmDatasets:
    train: LmStream
    validation: LmStream
    test: LmStream
    synthetic: bool = True


def make_lm_datasets(cfg, seq_len: int = 128) -> LmDatasets:
    return LmDatasets(
        train=LmStream(cfg, seq_len, seed=0),
        validation=LmStream(cfg, seq_len, seed=7_000_000),
        test=LmStream(cfg, seq_len, seed=8_000_000),
    )


def make_lm_eval_fn(apply_fn, batch_size: int = 32, num_batches: int = 4):
    """Next-token accuracy over fixed batches; matches the loop's
    ``eval_fn(state, split) -> float`` signature.

    ``apply_fn(params, tokens) -> logits`` (deterministic apply).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _acc(params, tokens):
        logits = apply_fn(params, tokens)
        pred = jnp.argmax(logits[:, :-1], -1)
        correct = (pred == tokens[:, 1:]).astype(jnp.float32)
        return correct.sum(), jnp.float32(correct.size)

    def evaluate(state, split) -> float:
        from ..parallel.sharding import multihost_replicated_put
        put = multihost_replicated_put(state.params)
        num, den = 0.0, 0.0
        for batch in split.fixed_batches(batch_size, num_batches):
            n, d = _acc(state.params, put(batch["tokens"]))
            num += float(n)
            den += float(d)
        return num / max(den, 1.0)

    return evaluate
