"""Causal-LM data streams for the GPT-mini workload: real text corpus or
synthetic.

Mirrors the reference's data-loader contract (``read_data_sets(data_dir)``
with a graceful source decision, reference ``distributed.py:6,38``): when
``data_dir`` holds ``*.txt`` files they become the corpus — byte-level
(vocab 256) by default, so any text trains as-is, or subword-tokenized with
``tokenizer="bpe"`` (:mod:`.tokenizer`, trained on the corpus's train split
only) — split 90/5/5 into contiguous train/validation/test regions.
Otherwise streams fall back to deterministic position-dependent-bigram
sequences (:func:`..models.gpt.synthetic_lm_batch`) that a decoder can
actually learn, behind the reference's ``next_batch`` API.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass

import numpy as np


class LmStream:
    """Batch stream with ``next_batch``; each call advances the sample seed."""

    def __init__(self, cfg, seq_len: int, seed: int):
        self.cfg = cfg
        self.seq_len = seq_len
        self._seed0 = seed
        self._seed = seed

    def next_batch(self, batch_size: int) -> dict:
        from ..models.gpt import synthetic_lm_batch
        batch = synthetic_lm_batch(self._seed, batch_size, self.seq_len,
                                   self.cfg)
        self._seed += 1
        return batch

    def shard(self, index: int, count: int) -> "LmStream":
        """Disjoint per-process stream (multi-controller sharded feed)."""
        del count
        return LmStream(self.cfg, self.seq_len,
                        self._seed + (index + 1) * 1_000_003)

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        from ..models.gpt import synthetic_lm_batch
        return [synthetic_lm_batch(20_000_000 + self._seed0 + i,
                                   batch_size, self.seq_len, self.cfg)
                for i in range(num_batches)]


class ByteLmStream:
    """Random fixed-length byte windows over a corpus region; same
    ``next_batch``/``fixed_batches`` API as :class:`LmStream`."""

    def __init__(self, data: np.ndarray, seq_len: int, seed: int):
        if len(data) <= seq_len:
            raise ValueError(f"corpus region of {len(data)} bytes is too "
                             f"short for seq_len={seq_len}")
        self.data = data
        self.seq_len = seq_len
        self._seed0 = seed
        self._seed = seed

    def _windows(self, rng: np.random.Generator, batch_size: int) -> dict:
        # +1: the high bound is exclusive; the last valid start position
        # len(data) - seq_len must remain drawable or the region's final
        # byte would never appear in any batch.
        starts = rng.integers(0, len(self.data) - self.seq_len + 1,
                              size=batch_size)
        toks = np.stack([self.data[s:s + self.seq_len] for s in starts])
        return {"tokens": toks.astype(np.int32)}

    def next_batch(self, batch_size: int) -> dict:
        batch = self._windows(np.random.default_rng(self._seed), batch_size)
        self._seed += 1
        return batch

    def shard(self, index: int, count: int) -> "ByteLmStream":
        """Disjoint per-process stream (multi-controller sharded feed)."""
        del count
        return ByteLmStream(self.data, self.seq_len,
                            self._seed + (index + 1) * 1_000_003)

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        return [self._windows(
                    np.random.default_rng(20_000_000 + self._seed0 + i),
                    batch_size)
                for i in range(num_batches)]


def load_byte_corpus(data_dir: str | None) -> np.ndarray | None:
    """Concatenated bytes of ``<data_dir>/*.txt`` (sorted), or None.

    ``*.txt`` only, deliberately: ``--data_dir`` defaults to the MNIST
    directory, whose IDX files must not silently become an LM corpus.
    """
    if not data_dir or not os.path.isdir(data_dir):
        return None
    paths = sorted(glob.glob(os.path.join(data_dir, "*.txt")))
    if not paths:
        return None
    def read_bytes(path):
        with open(path, "rb") as fh:
            return np.frombuffer(fh.read(), np.uint8)

    return np.concatenate([read_bytes(p) for p in paths])


@dataclass
class LmDatasets:
    train: LmStream
    validation: LmStream
    test: LmStream
    synthetic: bool = True


def make_lm_datasets(cfg, seq_len: int = 128,
                     data_dir: str | None = None,
                     tokenizer: str = "byte",
                     bpe_vocab: int = 512,
                     tokenizer_path: str | None = None) -> LmDatasets:
    """``tokenizer``: "byte" (ids = bytes, vocab 256) or "bpe" (byte-level
    BPE trained on the train region up to ``bpe_vocab`` tokens — the model's
    vocab must be >= that).  ``tokenizer_path`` persists the trained merge
    table (and an identity table for "byte") so eval/generate can decode
    ids back to text; every process derives the identical vocabulary
    deterministically, no broadcast needed."""
    if tokenizer not in ("byte", "bpe"):
        raise ValueError(f"tokenizer must be 'byte' or 'bpe', got {tokenizer!r}")
    corpus = load_byte_corpus(data_dir)
    if corpus is not None:
        n = len(corpus)
        train_end, val_end = int(n * 0.9), int(n * 0.95)
        # Every 90/5/5 region must fit at least one window; below that the
        # source decision stays graceful — warn and use the synthetic stream.
        min_bytes = int((seq_len + 1) / 0.05) + 1
        if n - val_end <= seq_len or val_end - train_end <= seq_len:
            print(f"WARNING: byte corpus under {data_dir} has {n:,} bytes; "
                  f"need > {min_bytes:,} for seq_len={seq_len} "
                  "(each 5% validation/test split must exceed one window) — "
                  "falling back to the synthetic stream")
            corpus = None
    if corpus is not None and tokenizer == "bpe":
        from .tokenizer import BpeTokenizer
        tok = BpeTokenizer.train(corpus[:train_end], bpe_vocab)
        regions = [corpus[:train_end], corpus[train_end:val_end],
                   corpus[val_end:]]
        ids = [tok.encode(r) for r in regions]
        if any(len(r) <= seq_len for r in ids[1:]):
            print(f"WARNING: BPE-encoded corpus regions "
                  f"{[len(r) for r in ids]} tokens; each validation/test "
                  f"region must exceed seq_len={seq_len} — falling back to "
                  "the synthetic stream")
            corpus = None
        else:
            if tokenizer_path:
                tok.save(tokenizer_path)
            n_ids = sum(len(r) for r in ids)
            print(f"gpt bpe corpus: {n:,} bytes -> {n_ids:,} tokens "
                  f"(vocab {tok.vocab_size}, {n / max(n_ids, 1):.2f} "
                  f"bytes/token) from {data_dir}/*.txt (train {len(ids[0]):,}"
                  f" / validation {len(ids[1]):,} / test {len(ids[2]):,})")
            return LmDatasets(
                train=ByteLmStream(ids[0], seq_len, seed=0),
                validation=ByteLmStream(ids[1], seq_len, seed=7_000_000),
                test=ByteLmStream(ids[2], seq_len, seed=8_000_000),
                synthetic=False,
            )
    if corpus is not None:
        if tokenizer_path:
            from .tokenizer import BpeTokenizer
            BpeTokenizer([]).save(tokenizer_path)  # identity: ids = bytes
        print(f"gpt byte corpus: {n:,} bytes from {data_dir}/*.txt "
              f"(train {train_end:,} / validation {val_end - train_end:,} / "
              f"test {n - val_end:,})")
        return LmDatasets(
            train=ByteLmStream(corpus[:train_end], seq_len, seed=0),
            validation=ByteLmStream(corpus[train_end:val_end], seq_len,
                                    seed=7_000_000),
            test=ByteLmStream(corpus[val_end:], seq_len, seed=8_000_000),
            synthetic=False,
        )
    return LmDatasets(
        train=LmStream(cfg, seq_len, seed=0),
        validation=LmStream(cfg, seq_len, seed=7_000_000),
        test=LmStream(cfg, seq_len, seed=8_000_000),
    )


def make_lm_eval_fn(apply_fn, batch_size: int = 32, num_batches: int = 4):
    """Next-token accuracy over fixed batches; matches the loop's
    ``eval_fn(state, split) -> float`` signature.

    ``apply_fn(params, tokens) -> logits`` (deterministic apply).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _acc(params, tokens):
        logits = apply_fn(params, tokens)
        pred = jnp.argmax(logits[:, :-1], -1)
        correct = (pred == tokens[:, 1:]).astype(jnp.float32)
        return correct.sum(), jnp.float32(correct.size)

    def evaluate(state, split) -> float:
        from ..parallel.sharding import multihost_replicated_put
        put = multihost_replicated_put(state.params)
        num, den = 0.0, 0.0
        for batch in split.fixed_batches(batch_size, num_batches):
            n, d = _acc(state.params, put(batch["tokens"]))
            num += float(n)
            den += float(d)
        return num / max(den, 1.0)

    return evaluate
