"""Byte-pair-encoding tokenizer for the LM corpus path.

The reference trains on fixed 784-float vectors and has no text pipeline at
all (reference ``distributed.py:6,38,75``); GPT-mini's real-text mode
(``data/lm.py``) is beyond-parity surface, and this module upgrades it from
raw bytes (vocab 256) to learned subword units: ``--gpt_tokenizer=bpe``
trains a byte-level BPE vocabulary on the corpus's train split, shrinking
sequences-per-character so a fixed ``--gpt_seq_len`` window covers ~2-4x the
text.

The hot loops — pair counting / merge compaction over the whole corpus for
training, and rank-by-rank merge application for encoding — run in C++
(``distributed_tensorflow_tpu/csrc/tokenizer/bpe.cc``) over a ctypes C ABI, the same native-build pattern
as the coordination service.  A pure-NumPy fallback keeps the module usable
(slowly) if the native build is unavailable.

Determinism: training is a pure function of (corpus bytes, vocab_size) —
ties broken toward the numerically smallest pair — so every process in a
multi-controller run derives the identical vocabulary independently; no
broadcast is needed.  ``save``/``load`` persist the merge table as JSON for
reuse at generate/eval time.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading

import numpy as np

from ..utils.native import build_and_load

_LIB_NAME = "libdtfbpe.so"
_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.normpath(
    os.path.join(_HERE, "..", "csrc", "tokenizer", "bpe.cc"))

_lib = None
_lib_lock = threading.Lock()


def _load_library() -> ctypes.CDLL | None:
    """Build (if stale) and load the native library; None if unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            lib = build_and_load(os.path.join(_HERE, _LIB_NAME), _SRC)
        except (OSError, subprocess.CalledProcessError):
            return None
        lib.dtf_bpe_train.restype = ctypes.c_int
        lib.dtf_bpe_train.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int32)]
        lib.dtf_bpe_encode.restype = ctypes.c_int64
        lib.dtf_bpe_encode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32)]
        _lib = lib
        return _lib


def _as_u8(data) -> np.ndarray:
    arr = np.ascontiguousarray(np.frombuffer(bytes(data), np.uint8)
                               if isinstance(data, (bytes, bytearray))
                               else np.asarray(data, np.uint8))
    return arr


# ------------------------------------------------------- NumPy fallback


def _merge_pass_np(seq: np.ndarray, a: int, b: int, new_id: int) -> np.ndarray:
    """Non-overlapping left-to-right replacement of (a, b) -> new_id.

    Vectorized: candidate positions are pair starts; overlapping runs
    (e.g. 'aaa' for rule (a, a)) keep alternating members only, matching the
    C++ scan's greedy semantics.
    """
    if len(seq) < 2:
        return seq
    hits = np.flatnonzero((seq[:-1] == a) & (seq[1:] == b))
    if len(hits) == 0:
        return seq
    if a == b:
        # Greedy left-to-right within each run of consecutive hits: keep
        # every other hit (runs of equal tokens are the only overlap case).
        keep = []
        prev = -2
        for h in hits:
            if h == prev + 1:
                continue        # overlaps the pair we just merged
            keep.append(h)
            prev = h
        hits = np.asarray(keep, hits.dtype)
    out = seq.copy()
    out[hits] = new_id
    mask = np.ones(len(seq), bool)
    mask[hits + 1] = False
    return out[mask]


def _train_np(data: np.ndarray, max_merges: int,
              min_pair_count: int) -> list[tuple[int, int]]:
    seq = data.astype(np.int32)
    merges: list[tuple[int, int]] = []
    min_pair_count = max(min_pair_count, 2)
    for rank in range(max_merges):
        if len(seq) < 2:
            break
        keys = seq[:-1].astype(np.int64) * (1 << 32) + seq[1:]
        uniq, counts = np.unique(keys, return_counts=True)
        best = counts.max()
        if best < min_pair_count:
            break
        cand = uniq[counts == best].min()      # smallest pair wins ties
        a, b = int(cand >> 32), int(cand & 0xFFFFFFFF)
        merges.append((a, b))
        seq = _merge_pass_np(seq, a, b, 256 + rank)
    return merges


def _encode_np(data: np.ndarray, merges: list[tuple[int, int]]) -> np.ndarray:
    seq = data.astype(np.int32)
    for rank, (a, b) in enumerate(merges):
        if len(seq) < 2:
            break
        seq = _merge_pass_np(seq, a, b, 256 + rank)
    return seq


# ------------------------------------------------------------ tokenizer


class BpeTokenizer:
    """Byte-level BPE: base vocab = 256 bytes, merge rank r = token 256+r."""

    def __init__(self, merges: list[tuple[int, int]]):
        self.merges = [(int(a), int(b)) for a, b in merges]
        # token id -> bytes, built by replaying the merge table.
        table = [bytes([i]) for i in range(256)]
        for a, b in self.merges:
            table.append(table[a] + table[b])
        self._bytes = table

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    # -- training ---------------------------------------------------------

    @classmethod
    def train(cls, data, vocab_size: int, *, min_pair_count: int = 2,
              max_train_bytes: int = 8 << 20) -> "BpeTokenizer":
        """Train on a byte corpus; ``vocab_size`` includes the 256 bytes.

        Training runs on at most ``max_train_bytes`` (the corpus prefix) —
        merge statistics saturate long before that; encoding always covers
        the full corpus.
        """
        if vocab_size < 256:
            raise ValueError(f"vocab_size must be >= 256, got {vocab_size}")
        arr = _as_u8(data)[:max_train_bytes]
        max_merges = vocab_size - 256
        lib = _load_library()
        if lib is None:
            return cls(_train_np(arr, max_merges, min_pair_count))
        out = np.empty((max(max_merges, 1), 2), np.int32)
        n = lib.dtf_bpe_train(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(arr),
            max_merges, min_pair_count,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return cls([tuple(p) for p in out[:n]])

    # -- encode / decode --------------------------------------------------

    def encode(self, data) -> np.ndarray:
        """bytes -> int32 token ids."""
        arr = _as_u8(data)
        if not self.merges or len(arr) == 0:
            return arr.astype(np.int32)
        lib = _load_library()
        if lib is None:
            return _encode_np(arr, self.merges)
        merges = np.ascontiguousarray(np.asarray(self.merges, np.int32))
        out = np.empty(len(arr), np.int32)
        n = lib.dtf_bpe_encode(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(arr),
            merges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(self.merges),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out[:n].copy()

    def decode(self, ids) -> bytes:
        """int ids -> bytes.  Ids beyond the trained vocabulary decode to
        U+FFFD: the model's embedding is padded up to ``--gpt_bpe_vocab``
        even when the corpus yields fewer merges, so sampling can legally
        emit ids the merge table never produced."""
        table = self._bytes
        rep = "�".encode("utf-8")
        return b"".join(
            table[i] if 0 <= i < len(table) else rep
            for i in (int(i) for i in np.asarray(ids).ravel()))

    # -- persistence ------------------------------------------------------

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # Per-pid temp name: in multi-process runs every worker derives (and
        # may save) the identical table; os.replace keeps the write atomic.
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump({"version": 1, "kind": "byte_bpe",
                       "merges": self.merges}, fh)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "BpeTokenizer":
        with open(path) as fh:
            blob = json.load(fh)
        if blob.get("kind") != "byte_bpe":
            raise ValueError(f"{path} is not a byte_bpe tokenizer file")
        return cls([tuple(m) for m in blob["merges"]])
