"""Data pipeline (C3) — the ``input_data.read_data_sets`` equivalent.

The reference loads MNIST into host memory and batches with a shuffled
``next_batch`` (reference ``distributed.py:6,38,137``).  Same API here:
:func:`read_data_sets` returns ``Datasets(train, validation, test)`` where each
split is a :class:`DataSet` with ``.images``, ``.labels``, ``.next_batch(n)``.

Loaders read the standard IDX files from ``data_dir`` when present; with no
files (this image has zero network egress) they fall back to a deterministic
synthetic dataset whose class structure is learnable, so convergence tests and
benchmarks behave like the real thing.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass, field

import numpy as np

MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


class DataSet:
    """In-memory split with shuffled ``next_batch`` (reference ``distributed.py:137``).

    ``augment_fn(images, rng) -> images`` (optional) is applied to every
    training batch after selection — host-side numpy, overlapped with device
    compute by the input prefetcher.  Eval paths read ``.images`` directly
    and stay un-augmented."""

    def __init__(self, images: np.ndarray, labels: np.ndarray, *,
                 seed: int = 0, augment_fn=None):
        assert images.shape[0] == labels.shape[0]
        self.images = images
        self.labels = labels
        self._num = images.shape[0]
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self._perm = self._rng.permutation(self._num)
        self._pos = 0
        self._augment_fn = augment_fn
        self.epochs_completed = 0

    def shard(self, index: int, count: int) -> "DataSet":
        """Per-process slice for the multi-controller sharded feed: every
        ``count``-th example starting at ``index`` (strided — preserves class
        balance), with its own shuffle stream.  Processes then feed disjoint
        data; the global batch is their concatenation."""
        return DataSet(self.images[index::count], self.labels[index::count],
                       seed=self._seed * 1000 + index + 1,
                       augment_fn=self._augment_fn)

    @property
    def num_examples(self) -> int:
        return self._num

    def next_batch(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        """Sequential batches over a shuffled order; reshuffles each epoch."""
        if self._pos + batch_size > self._num:
            self.epochs_completed += 1
            self._perm = self._rng.permutation(self._num)
            self._pos = 0
        idx = self._perm[self._pos:self._pos + batch_size]
        self._pos += batch_size
        images = self.images[idx]
        if self._augment_fn is not None:
            images = self._augment_fn(images, self._rng)
        return images, self.labels[idx]


@dataclass
class Datasets:
    train: DataSet
    validation: DataSet
    test: DataSet
    synthetic: bool = field(default=False)


class Uint8FeedSplit:
    """Train-feed adapter: ships images host→device as uint8 (4x fewer feed
    bytes than float32 — the production input-pipeline convention), with the
    models dividing by 255 on device (their integer-input path).

    Pixel sources here are 8-bit to begin with (MNIST IDX / CIFAR pickles,
    loaded as ``uint8/255``), so ``round(x*255)`` recovers the original
    bytes exactly; the synthetic streams lose at most 1/510 per pixel.
    Wraps ``next_batch`` only — eval paths read ``.images`` (float) directly.
    """

    def __init__(self, split: DataSet):
        self._split = split

    def next_batch(self, batch_size: int):
        images, labels = self._split.next_batch(batch_size)
        if images.dtype == np.float32:
            images = np.rint(np.clip(images, 0.0, 1.0) * 255.0).astype(
                np.uint8)
        return images, labels

    def shard(self, index: int, count: int) -> "Uint8FeedSplit":
        return Uint8FeedSplit(self._split.shard(index, count))

    def __getattr__(self, name):
        return getattr(self._split, name)


def uint8_feed(datasets: Datasets) -> Datasets:
    """Wrap the training split for uint8 host→device feeding."""
    return Datasets(train=Uint8FeedSplit(datasets.train),
                    validation=datasets.validation, test=datasets.test,
                    synthetic=datasets.synthetic)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(data_dir: str, base: str) -> str | None:
    for cand in (base, base + ".gz"):
        p = os.path.join(data_dir, cand)
        if os.path.exists(p):
            return p
    return None


def _one_hot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    out = np.zeros((labels.shape[0], num_classes), np.float32)
    out[np.arange(labels.shape[0]), labels.astype(np.int64)] = 1.0
    return out


def synthetic_classification(num: int, dim: int, num_classes: int, *,
                             seed: int, noise: float = 0.35,
                             centers_seed: int = 7) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable dataset: class-dependent means + gaussian noise.

    A linear/MLP model trained on this converges quickly, which is what the
    reference's convergence-as-test strategy needs (SURVEY §4).  ``centers_seed``
    fixes the class structure so differently-seeded splits (train vs test) are
    drawn from the *same* distribution.
    """
    rng = np.random.default_rng(seed)
    centers_rng = np.random.default_rng(centers_seed)
    centers = centers_rng.normal(0.5, 0.25, size=(num_classes, dim)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=num)
    images = centers[labels] + rng.normal(0.0, noise, size=(num, dim)).astype(np.float32)
    images = np.clip(images, 0.0, 1.0).astype(np.float32)
    return images, labels


def read_data_sets(data_dir: str, one_hot: bool = True, *,
                   validation_size: int = 5000,
                   synthetic_train_size: int = 20000) -> Datasets:
    """MNIST with the reference's split shape: train/validation/test.

    Real IDX files in ``data_dir`` are used when present (images scaled to
    [0,1], labels one-hot, 5000-example validation split carved from train —
    matching the TF tutorial loader the reference calls).  Otherwise a
    deterministic synthetic stand-in with the same shapes is returned.
    """
    paths = {k: _find(data_dir, v) for k, v in MNIST_FILES.items()}
    if all(paths.values()):
        train_images = _read_idx(paths["train_images"]).reshape(-1, 784).astype(np.float32) / 255.0
        train_labels = _read_idx(paths["train_labels"])
        test_images = _read_idx(paths["test_images"]).reshape(-1, 784).astype(np.float32) / 255.0
        test_labels = _read_idx(paths["test_labels"])
        synthetic = False
    else:
        train_images, train_labels = synthetic_classification(
            synthetic_train_size + validation_size, 784, 10, seed=1234)
        test_images, test_labels = synthetic_classification(5000, 784, 10, seed=5678)
        synthetic = True

    if one_hot:
        train_labels_e = _one_hot(train_labels, 10)
        test_labels_e = _one_hot(test_labels, 10)
    else:
        train_labels_e = train_labels.astype(np.int32)
        test_labels_e = test_labels.astype(np.int32)

    val_images = train_images[:validation_size]
    val_labels = train_labels_e[:validation_size]
    trn_images = train_images[validation_size:]
    trn_labels = train_labels_e[validation_size:]

    return Datasets(
        train=DataSet(trn_images, trn_labels, seed=0),
        validation=DataSet(val_images, val_labels, seed=1),
        test=DataSet(test_images, test_labels_e, seed=2),
        synthetic=synthetic,
    )


CIFAR10_TRAIN_BATCHES = [f"data_batch_{i}" for i in range(1, 6)]
CIFAR10_TEST_BATCH = "test_batch"


def cifar_augment(images: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Standard CIFAR train-time augmentation: reflect-pad 4, random 32x32
    crop, random horizontal flip.  Flat [B, 3072] HWC in, same out.
    Vectorized (one gather + one flip) — this can sit on the step critical
    path when prefetch is off (multi-controller runs)."""
    B = images.shape[0]
    x = images.reshape(B, 32, 32, 3)
    padded = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    offsets = rng.integers(0, 9, size=(B, 2))
    flips = rng.random(B) < 0.5
    # windows: [B, 9, 9, 3, 32, 32] — all crop positions; one fancy-index
    # gather picks each sample's (dy, dx).
    windows = np.lib.stride_tricks.sliding_window_view(
        padded, (32, 32), axis=(1, 2))
    out = windows[np.arange(B), offsets[:, 0], offsets[:, 1]]  # [B, 3, 32, 32]
    out = out.transpose(0, 2, 3, 1).copy()                     # [B, 32, 32, 3]
    out[flips] = out[flips, :, ::-1]
    return out.reshape(B, 3072)


def read_cifar10(data_dir: str, one_hot: bool = True, *,
                 validation_size: int = 5000,
                 synthetic_train_size: int = 20000,
                 augment: bool = False) -> Datasets:
    """CIFAR-10 (for the ResNet-20 config in BASELINE.json), pickle or synthetic.

    Images are returned flattened HWC float32 in [0,1]; models reshape to
    (32, 32, 3).
    """
    import pickle

    def find_batch(name):
        for sub in ("", "cifar-10-batches-py"):
            p = os.path.join(data_dir, sub, name)
            if os.path.exists(p):
                return p
        return None

    train_paths = [find_batch(b) for b in CIFAR10_TRAIN_BATCHES]
    test_path = find_batch(CIFAR10_TEST_BATCH)
    if all(train_paths) and test_path:
        imgs, labels = [], []
        for p in train_paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            imgs.append(d[b"data"])
            labels.append(np.asarray(d[b"labels"]))
        train_images = np.concatenate(imgs).astype(np.float32) / 255.0
        train_labels = np.concatenate(labels)
        with open(test_path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        test_images = d[b"data"].astype(np.float32) / 255.0
        test_labels = np.asarray(d[b"labels"])
        # CHW -> HWC flat
        train_images = train_images.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).reshape(-1, 3072)
        test_images = test_images.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).reshape(-1, 3072)
        synthetic = False
    else:
        train_images, train_labels = synthetic_classification(
            synthetic_train_size + validation_size, 3072, 10, seed=4321, noise=0.25)
        test_images, test_labels = synthetic_classification(5000, 3072, 10, seed=8765, noise=0.25)
        synthetic = True

    if one_hot:
        train_labels_e = _one_hot(train_labels, 10)
        test_labels_e = _one_hot(test_labels, 10)
    else:
        train_labels_e = train_labels.astype(np.int32)
        test_labels_e = test_labels.astype(np.int32)

    if augment and synthetic:
        # The synthetic fallback's classes are iid per-pixel gaussians with
        # no spatial structure — crops/flips would just destroy the signal.
        print("WARNING: --data_augmentation disabled: no CIFAR batches under "
              f"{data_dir}; the synthetic fallback has no spatial structure "
              "to augment")
        augment = False
    return Datasets(
        train=DataSet(train_images[validation_size:],
                      train_labels_e[validation_size:], seed=0,
                      augment_fn=cifar_augment if augment else None),
        validation=DataSet(train_images[:validation_size], train_labels_e[:validation_size], seed=1),
        test=DataSet(test_images, test_labels_e, seed=2),
        synthetic=synthetic,
    )
