"""Synthetic MLM data streams for the BERT-tiny config (BASELINE.json #5).

No tokenizer or corpus ships in the image, so streams generate deterministic
position-dependent-bigram sequences (see
:func:`..models.bert.synthetic_mlm_batch`) that an MLM objective can actually
learn.  The stream mimics the :class:`..data.datasets.DataSet` batch API so the
training loop treats it like any split.
"""

from __future__ import annotations

from dataclasses import dataclass


class MlmStream:
    """Batch stream with ``next_batch``; each call advances the sample seed."""

    def __init__(self, cfg, seq_len: int, seed: int):
        self.cfg = cfg
        self.seq_len = seq_len
        self._seed0 = seed
        self._seed = seed

    def next_batch(self, batch_size: int) -> dict:
        from ..models.bert import synthetic_mlm_batch
        batch = synthetic_mlm_batch(self._seed, batch_size, self.seq_len, self.cfg)
        self._seed += 1
        return batch

    def shard(self, index: int, count: int) -> "MlmStream":
        """Disjoint per-process stream (multi-controller sharded feed)."""
        del count
        return MlmStream(self.cfg, self.seq_len,
                         self._seed + (index + 1) * 1_000_003)

    def fixed_batches(self, batch_size: int, num_batches: int) -> list[dict]:
        """Deterministic eval batches — stable per split (keyed off the split's
        base seed, so validation and test evaluate *different* sequences)."""
        from ..models.bert import synthetic_mlm_batch
        return [synthetic_mlm_batch(10_000_000 + self._seed0 + i,
                                    batch_size, self.seq_len, self.cfg)
                for i in range(num_batches)]


@dataclass
class MlmDatasets:
    train: MlmStream
    validation: MlmStream
    test: MlmStream
    synthetic: bool = True


def make_mlm_datasets(cfg, seq_len: int = 128) -> MlmDatasets:
    return MlmDatasets(
        train=MlmStream(cfg, seq_len, seed=0),
        validation=MlmStream(cfg, seq_len, seed=5_000_000),
        test=MlmStream(cfg, seq_len, seed=6_000_000),
    )


def make_mlm_eval_fn(apply_fn, batch_size: int = 32, num_batches: int = 4):
    """Masked-position accuracy over fixed batches of a stream split.

    ``apply_fn(params, input_ids, attention_mask) -> logits``.  Signature
    matches the loop's ``eval_fn(state, split) -> float``.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _acc(params, batch):
        logits = apply_fn(params, batch["input_ids"], batch["attention_mask"])
        correct = (jnp.argmax(logits, -1) == batch["labels"]).astype(jnp.float32)
        w = batch["label_weights"]
        return (correct * w).sum(), w.sum()

    def evaluate(state, split) -> float:
        from ..parallel.sharding import multihost_replicated_put
        put = multihost_replicated_put(state.params)
        num, den = 0.0, 0.0
        for batch in split.fixed_batches(batch_size, num_batches):
            n, d = _acc(state.params, jax.tree.map(put, batch))
            num += float(n)
            den += float(d)
        return num / max(den, 1.0)

    return evaluate
