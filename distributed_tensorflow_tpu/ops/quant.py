"""Weight-only int8 quantization — HBM bandwidth relief for inference.

TPU decode is memory-bound: every generated token re-reads the full weight
set, so at bf16 the decode rate is capped by HBM bytes/step.  Storing weights
as **per-channel symmetric int8** halves those bytes; the dequantize
(``q * scale``) runs inside the jitted step, where XLA fuses it into the
consuming matmul — weights stay int8 in HBM, compute stays bf16 on the MXU.
(The reference had no quantization story at all; its inference was the same
float graph as training, reference ``distributed.py:78-84``.)

Representation: :func:`quantize_tree` maps each eligible weight leaf to a
``{"q": int8, "s": float32}`` dict (scale per output channel and per small
fused-projection axis — see :func:`quantize_leaf`); small or integer leaves
pass through unchanged.  :func:`dequantize_tree` restores a compute-dtype
tree with identical structure to the original params.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

_QKEYS = frozenset({"q", "s"})


def resolve_kv_dtype(name: str):
    """KV-cache dtype from its CLI spelling — ONE mapping shared by the
    decode entry points (``models/gpt._decode_setup``) and the serving
    engine, so "float8" always means ``float8_e4m3fn`` everywhere.
    ``""`` means "the compute dtype" and maps to None (caller default)."""
    table = {"": None, "bfloat16": jnp.bfloat16,
             "float8": jnp.float8_e4m3fn}
    if name not in table:
        raise ValueError(
            f"kv_dtype must be '', 'bfloat16' or 'float8', got {name!r}")
    return table[name]


def validate_quantize(name: str) -> str:
    """Weight-storage mode from its CLI spelling — ONE validation shared
    by the decode entry points, the speculative paths, and the serving
    engine (they must reject the same strings the same way)."""
    if name not in ("", "int8"):
        raise ValueError(f"quantize must be '' or 'int8', got {name!r}")
    return name


def prepare_inference_tree(params: Any, quantize: str) -> Any:
    """Host param tree -> the tree an inference path should CARRY across
    dispatches: per-channel int8 + scales under ``quantize="int8"``
    (half the HBM weight bytes), the original tree otherwise.  Pair with
    :func:`load_inference_tree` inside the jitted consumer."""
    validate_quantize(quantize)
    return quantize_tree(params) if quantize == "int8" else params


def load_inference_tree(tree: Any, quantize: str, dtype) -> Any:
    """Inverse of :func:`prepare_inference_tree`, called INSIDE the jitted
    step so XLA fuses the dequant multiply into the consuming matmuls —
    the shared weight-loading recipe of ``generate_cached``, the
    speculative decoders, and the serving engine."""
    if quantize == "int8":
        return dequantize_tree(tree, dtype)
    return tree


def _is_qleaf(x: Any) -> bool:
    return isinstance(x, dict) and frozenset(x.keys()) == _QKEYS


def quantize_leaf(w: jax.Array) -> dict:
    """Per-channel symmetric int8: ``w ≈ q * s`` with |q| <= 127.

    Scales vary along the LAST axis plus any small inner axes (size <= 4,
    e.g. the fused-projection axis of GPT's qkv ``[hidden, 3, H, D]`` —
    Q/K/V get distinct scales instead of sharing one); all other axes —
    the contraction dims of the kernels here, including both contraction
    axes of ``DenseGeneral(axis=(-2, -1))``'s ``[H, D, out]`` kernels —
    are reduced, keeping the scale tensor tiny next to the int8 payload.
    Dequant is exact elementwise regardless of grouping, so granularity
    trades only scale bytes for fidelity.
    """
    w32 = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim - 1)
                        if not (0 < i and w.shape[i] <= 4))
    amax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def quantize_tree(params: Any, *, min_size: int = 4096) -> Any:
    """Quantize every float leaf with >= ``min_size`` elements.

    Small leaves (biases, LayerNorm gains) carry negligible bytes and the
    most precision sensitivity — they stay in their original dtype.
    """
    def leaf(w):
        if (not hasattr(w, "dtype")
                or not jnp.issubdtype(w.dtype, jnp.floating)
                or w.ndim < 2 or w.size < min_size):
            return w
        return quantize_leaf(w)
    return jax.tree.map(leaf, params)


def dequantize_tree(qparams: Any, dtype=jnp.bfloat16) -> Any:
    """Rebuild a compute-dtype tree; called INSIDE the jitted consumer so
    XLA fuses the multiply into the matmul and HBM holds only int8."""
    def leaf(x):
        if _is_qleaf(x):
            return (x["q"].astype(jnp.float32) * x["s"]).astype(dtype)
        return x
    return jax.tree.map(leaf, qparams, is_leaf=_is_qleaf)


def quantized_bytes(qparams: Any) -> int:
    """Total parameter bytes as stored (int8 + scales + passthrough)."""
    total = 0
    for leaf in jax.tree.leaves(qparams):
        total += leaf.size * leaf.dtype.itemsize
    return total
