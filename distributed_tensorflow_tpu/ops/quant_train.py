"""Int8 quantized TRAINING matmuls — opening the MXU's int8 rate (2x bf16).

The v5e MXU runs int8×int8→int32 at twice the bf16 FLOP rate (measured on
this chip: 343 TOPS pipelined vs 179 bf16 TFLOP/s at 8192³; 271 vs 162 at
the GPT MLP's own shapes — 1.7-1.9x).  The round-3 profile put 79.5% of
flagship-step device time in matmuls, so quantized training is the one
lever left on headline MFU (VERDICT r3 #2).  (The reference trained pure
float32 and had no quantization story at all, reference
``distributed.py:78-84``.)

Scheme — the SwitchBack recipe (per-row dynamic activation scales, int8
forward and input-gradient matmuls, full-precision weight-gradient
matmul):

- **forward**  ``y = (q(x)·q(w)) * sx * sw``: activations quantized
  per-ROW (each token its own scale), weights per-OUTPUT-CHANNEL — both
  scale vectors index non-contracted axes, so the int32 product is
  rescaled exactly.
- **dgrad** (int8): ``dx = (q(g)·q(wᵀ)) * sg * swᵀ`` — ``wᵀ`` is
  re-quantized per-column (the output axis of this product), again
  factorable.
- **wgrad** (bf16/f32): ``dw = xᵀ·g`` at full precision — the
  gradient-accumulation path is where int8 noise compounds into
  divergence, and it is 1/3 of the matmul FLOPs, so precision is kept
  where it matters (this is the error-compensation choice; the honest
  convergence delta is recorded by ``tests/test_int8_train.py`` and the
  bench's ``gpt_int8_*`` arm).

:class:`Int8Dense` is a drop-in for ``flax.linen.Dense``: same parameter
names ("kernel"/"bias"), same initializers, same tree — checkpoints are
interchangeable with the bf16 model, so a run can switch precision on
restore.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


def _quant_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-ROW (last axis reduced): returns (q, scale)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / s), -127, 127).astype(jnp.int8)
    return q, s


def _quant_cols(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-COLUMN (first axis reduced): returns (q, scale)."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=0, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return q, s


def _i8_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """int8 [M, K] @ int8 [K, N] -> int32 [M, N] on the MXU's int8 path."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x [M, K] @ w [K, N]`` with int8 forward/dgrad, f32 wgrad."""
    return _int8_fwd(x, w)[0]


def _int8_fwd(x, w):
    qx, sx = _quant_rows(x)
    qw, sw = _quant_cols(w)
    y = _i8_dot(qx, qw).astype(jnp.float32) * sx * sw
    return y.astype(x.dtype), (x, w)


def _int8_bwd(res, g):
    x, w = res
    qg, sg = _quant_rows(g)
    qwt, swt = _quant_cols(w.T)
    dx = (_i8_dot(qg, qwt).astype(jnp.float32) * sg * swt).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


int8_matmul.defvjp(_int8_fwd, _int8_bwd)


class Int8Dense(nn.Module):
    """``nn.Dense`` with the matmul routed through :func:`int8_matmul`.

    Identical parameter tree ("kernel" f32 [in, features], optional
    "bias") and initializers, so bf16 and int8 runs share checkpoints.
    The kernel is re-quantized inside every step — its quantization error
    therefore tracks the CURRENT weights (no staleness), at the cost of
    an elementwise pass that is negligible next to the matmul.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        lead = x.shape[:-1]
        y = int8_matmul(x.reshape(-1, x.shape[-1]).astype(self.dtype),
                        kernel)
        y = y.reshape(*lead, self.features)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            y = y + bias.astype(y.dtype)
        return y
