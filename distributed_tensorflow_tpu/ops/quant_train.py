"""Int8 quantized TRAINING matmuls — opening the MXU's int8 rate (2x bf16).

The v5e MXU runs int8×int8→int32 at twice the bf16 FLOP rate (measured on
this chip: 343 TOPS pipelined vs 179 bf16 TFLOP/s at 8192³; 271 vs 162 at
the GPT MLP's own shapes — 1.7-1.9x).  The round-3 profile put 79.5% of
flagship-step device time in matmuls, so quantized training is the one
lever left on headline MFU (VERDICT r3 #2).  (The reference trained pure
float32 and had no quantization story at all, reference
``distributed.py:78-84``.)

Scheme — the SwitchBack recipe (per-row dynamic activation scales, int8
forward and input-gradient matmuls, full-precision weight-gradient
matmul):

- **forward**  ``y = (q(x)·q(w)) * sx * sw``: activations quantized
  per-ROW (each token its own scale), weights per-OUTPUT-CHANNEL — both
  scale vectors index non-contracted axes, so the int32 product is
  rescaled exactly.
- **dgrad** (int8): ``dx = (q(g)·q(wᵀ)) * sg * swᵀ`` — ``wᵀ`` is
  re-quantized per-column (the output axis of this product), again
  factorable.
- **wgrad** (bf16/f32): ``dw = xᵀ·g`` at full precision — the
  gradient-accumulation path is where int8 noise compounds into
  divergence, and it is 1/3 of the matmul FLOPs, so precision is kept
  where it matters (this is the error-compensation choice; the honest
  convergence delta is recorded by ``tests/test_int8_train.py`` and the
  bench's ``gpt_int8_*`` arm).

The gelu MLP runs through FUSED pallas kernels by default
(:func:`int8_gelu_mlp`, gated by :func:`use_fused_mlp`): bias+gelu in
the forward epilogue, the gelu backward in the dgrad prologue, and — the
r5 unlock — an NT backward (``quantized_matmul_nt``) that reuses the
FORWARD's quantized weight with the per-column scale folded into the
incoming gradient, so the backward does no weight re-quantization and no
transpose.  Measured on the flagship step (L=8 H=2048 I=8192 B=8
S=1024): **1.017x over bf16 end-to-end** (164.0 vs 166.8 ms/step),
up from 0.84x for the r4 naive composition.  The engineering record of
what did NOT work on the way (XLA int8 formulation 0.96x; int8-transpose
weight prep 1.6 ms SLOWER than the f32 transpose; derivative-storage
epilogue 2.8 ms slower; in-kernel residual add 7 ms slower; int8
attention projections a wash) lives in BASELINE.md's int8 section.

:func:`int8_matmul` (the per-layer drop-in used for swiglu and the
``attn_int8`` projections) keeps the XLA formulation by default
(``FUSED_KERNEL_IN_STEP = False``): without the cross-layer fusion the
opaque pallas call still loses its epilogue fusions (r4 measurements:
fwd-only 182.1 ms vs XLA 179.9).

:class:`Int8Dense` is a drop-in for ``flax.linen.Dense``: same parameter
names ("kernel"/"bias"), same initializers, same tree — checkpoints are
interchangeable with the bf16 model, so a run can switch precision on
restore.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


def _quant_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-ROW (last axis reduced): returns (q, scale)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / s), -127, 127).astype(jnp.int8)
    return q, s


# Per-COLUMN weight quantization: ONE definition, shared with the fused
# pallas kernel so the two paths can never drift apart (the equivalence
# tests assume identical weight quantization).
from .pallas.quant_matmul import quantize_cols as _quant_cols  # noqa: E402


def _i8_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """int8 [M, K] @ int8 [K, N] -> int32 [M, N] on the MXU's int8 path."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)


#: Route int8_matmul's fwd/dgrad through the pallas fused-quantize kernel
#: on TPU.  OFF by default: the kernel wins in isolation but loses in the
#: full step (see the module docstring's measurements) — the flag exists
#: so the trade re-measures in one line when the composition changes.
#: Read at TRACE time: set it BEFORE the train step first compiles (a
#: flip in a running process is masked by the jit cache — restart or
#: jax.clear_caches() to re-measure).
FUSED_KERNEL_IN_STEP = False


def _use_fused_kernel(M: int, K: int, N: int) -> bool:
    """Gate for the pallas fused-quantize kernel (compiled Mosaic, tileable
    shapes, and the module-level opt-in)."""
    if not FUSED_KERNEL_IN_STEP:
        return False
    from .pallas.flash_attention import _gspmd_hazard
    from .pallas.quant_matmul import supported
    return (jax.default_backend() == "tpu" and supported(M, K, N)
            and not _gspmd_hazard())


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x [M, K] @ w [K, N]`` with int8 forward/dgrad, f32 wgrad."""
    return _int8_fwd(x, w)[0]


def _fwd_math(x, w):
    M, K = x.shape
    N = w.shape[1]
    if _use_fused_kernel(M, K, N):
        from .pallas.quant_matmul import quantize_cols, quantized_matmul
        qw, sw = quantize_cols(w)
        return quantized_matmul(x, qw, sw)
    qx, sx = _quant_rows(x)
    qw, sw = _quant_cols(w)
    y = _i8_dot(qx, qw).astype(jnp.float32) * sx * sw
    return y.astype(x.dtype)


def _int8_fwd(x, w):
    return _fwd_math(x, w), (x, w)


def _int8_bwd(res, g):
    x, w = res
    M, N = g.shape
    K = w.shape[0]
    if _use_fused_kernel(M, N, K):
        from .pallas.quant_matmul import quantize_cols, quantized_matmul
        qwt, swt = quantize_cols(w.T)
        dx = quantized_matmul(g, qwt, swt).astype(x.dtype)
    else:
        qg, sg = _quant_rows(g)
        qwt, swt = _quant_cols(w.T)
        dx = (_i8_dot(qg, qwt).astype(jnp.float32) * sg * swt).astype(
            x.dtype)
    dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


int8_matmul.defvjp(_int8_fwd, _int8_bwd)


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """``lax.dot_general`` drop-in that routes through :func:`int8_matmul`.

    Built for flax's ``Dense``/``DenseGeneral`` ``dot_general=`` injection
    point: attention projections (qkv/out) are plain matmuls with no
    activation epilogue, so the int8 MXU rate applies with none of the
    MLP path's gelu/preact tax.  Handles the Dense pattern — trailing
    contracting dims on ``lhs``, leading on ``rhs``, no batch dims — by
    flattening to 2D around :func:`int8_matmul` (int8 fwd/dgrad;
    wgrad accumulates in f32 but lands in the dtype flax promoted the
    kernel to — for a ``dtype=bf16`` module that is bf16, the SAME
    rounding point the plain bf16 ``DenseGeneral`` has, unlike
    :class:`Int8Dense`, which keeps the kernel f32 end to end).
    Anything else — including a ``preferred_element_type`` other than
    the lhs dtype, which the int8 path could not honor — falls back to
    the real ``lax.dot_general``.  ``precision`` is meaningless on the
    int8 path (the quantization IS the precision) and only honored on
    the fallback.
    """
    (lc, rc), (lb, rb) = dimension_numbers
    lc, rc = tuple(lc), tuple(rc)
    nl, nr = lhs.ndim, rhs.ndim
    dense_pattern = (not lb and not rb
                     and lc == tuple(range(nl - len(lc), nl))
                     and rc == tuple(range(len(rc)))
                     and preferred_element_type in (None, lhs.dtype))
    if not dense_pattern:
        return jax.lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type)
    K = 1
    for d in lc:
        K *= lhs.shape[d]
    lead = lhs.shape[:nl - len(lc)]
    tail = rhs.shape[len(rc):]
    N = 1
    for d in tail:
        N *= d
    y = int8_matmul(lhs.reshape(-1, K), rhs.reshape(K, N))
    return y.reshape(*lead, *tail)


#: Route the whole gelu MLP through the fused pallas kernels
#: (int8_gelu_mlp).  ON by default — this composition MEASURED FASTER
#: than bf16 (1.017x at the flagship shapes; see the module docstring).
#: Read at TRACE time, like FUSED_KERNEL_IN_STEP.
FUSED_MLP_IN_STEP = True


def use_fused_mlp(M: int, H: int, I: int) -> bool:
    """Gate for routing the WHOLE gelu MLP through the fused pallas
    kernels (``int8_gelu_mlp``): default-on flag, TPU backend, tileable
    shapes for every matmul in the pair (fwd M×H·H×I and M×I·I×H, NT
    dgrads — the dim SET is the same, so one check covers all), and no
    GSPMD hazard (compiled Mosaic calls cannot be auto-partitioned by a
    multi-chip jit outside shard_map — same fallback rule as the flash
    kernels; the XLA int8 formulation partitions fine and takes over)."""
    if not FUSED_MLP_IN_STEP:
        return False
    from .pallas.flash_attention import _gspmd_hazard
    from .pallas.quant_matmul import supported
    return (jax.default_backend() == "tpu" and supported(M, H, I)
            and not _gspmd_hazard())


@jax.custom_vjp
def int8_gelu_mlp(x: jax.Array, w_in: jax.Array, b_in: jax.Array,
                  w_out: jax.Array, b_out: jax.Array) -> jax.Array:
    """The whole GPT gelu MLP — ``(gelu(x@w_in + b_in))@w_out + b_out`` —
    through the fused quantize-matmul kernels, never returning to XLA
    between the first matmul and the last bias add.

    This is the r4 finding turned into code: the isolated pallas kernel
    beat bf16 1.6-2x at these shapes but LOST in-step because each opaque
    pallas call forfeited XLA's bias/gelu epilogue fusions and bought
    layout copies (``gpt_int8_note``).  Fusing the epilogue (bias+gelu on
    the forward, gelu-backward in the dgrad prologue) keeps that work in
    VMEM inside the kernels.

    Precision scheme is SwitchBack, same as :func:`int8_matmul`: int8
    forward and dgrad (per-(row, K-block) activation scales — finer than
    the XLA path's per-row), f32 wgrad.  Caller gates on
    :func:`use_fused_mlp`.
    """
    return _mlp_fwd(x, w_in, b_in, w_out, b_out)[0]


def _mlp_fwd(x, w_in, b_in, w_out, b_out):
    from .pallas.quant_matmul import quantize_cols, quantized_matmul
    interp = jax.default_backend() != "tpu"  # CPU CI runs the interpreter
    qwi, swi = quantize_cols(w_in)
    # block_m 256: the two-output (want_preact) call overflows the 16M
    # VMEM budget at full 512x2048 blocks; 256x2048 measured fastest of
    # the fitting configs.
    a, pre = quantized_matmul(x, qwi, swi, b_in, activation="gelu",
                              want_preact=True, block_m=256,
                              interpret=interp)
    qwo, swo = quantize_cols(w_out)
    # block_k 1024 on the single-output calls: measured ~3% faster than
    # the 512 default at the flagship shapes (fewer grid steps, same
    # VMEM headroom without a second output block).
    y = quantized_matmul(a, qwo, swo, b_out, block_k=1024,
                         interpret=interp)
    # Residuals carry the QUANTIZED weights (int8, 1/4 the f32 bytes):
    # the NT backward reuses them as-is — no re-quantization, no
    # transpose (see quantized_matmul_nt's scale-folding algebra).
    return y, (x, pre, a, qwi, swi, qwo, swo)


def _mlp_bwd(res, gy):
    from .pallas.quant_matmul import quantized_matmul_nt
    interp = jax.default_backend() != "tpu"
    x, pre, a, qwi, swi, qwo, swo = res
    # mlp_out: int8 dgrad, f32 wgrad (the SwitchBack split).  The NT
    # kernel reuses the FORWARD's quantized weight (fwd layout, col
    # scales folded into gy in the prologue) — the backward does no
    # weight re-quantization and no transpose, the two composition
    # taxes the r4 measurements identified (f32 w.T transposes ~2.6 ms,
    # re-quantize passes ~2 ms; the int8-transpose alternative measured
    # 1.6 ms SLOWER than the f32 one).
    da = quantized_matmul_nt(gy, qwo, swo, block_k=1024, interpret=interp)
    dw_out = jax.lax.dot_general(
        a, gy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.float32)
    db_out = jnp.sum(gy.astype(jnp.float32), axis=0)
    # gelu backward fused into the mlp_in dgrad prologue; g emitted once
    # from VMEM for the wgrad/bias-grad path.
    # bk stays 512 here: the two-output (want_g) variant at bk=1024
    # overflows scoped VMEM in-step (measured 18M vs the 16M limit).
    dx, g = quantized_matmul_nt(da, qwi, swi, pre, prologue="dgelu_fold",
                                want_g=True, interpret=interp)
    dw_in = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.float32)
    db_in = jnp.sum(g.astype(jnp.float32), axis=0)
    return dx, dw_in, db_in, dw_out, db_out


int8_gelu_mlp.defvjp(_mlp_fwd, _mlp_bwd)


#: Also fold the block's RESIDUAL ADD (``x + mlp(x)``) into the second
#: fused kernel's epilogue (int8_gelu_mlp_res).  OFF by default: at the
#: flagship shapes the extra [M, H] input block degraded the kernel's
#: pipelining more than the saved XLA add pass (measured 7 ms/step
#: slower — BASELINE.md int8 section); the fused form is kept wired so
#: the trade re-measures in one line when shapes or Mosaic change.
#: Read at TRACE time, like FUSED_MLP_IN_STEP.
FUSED_MLP_RESIDUAL = False


@jax.custom_vjp
def int8_gelu_mlp_res(x: jax.Array, w_in: jax.Array, b_in: jax.Array,
                      w_out: jax.Array, b_out: jax.Array,
                      res: jax.Array) -> jax.Array:
    """:func:`int8_gelu_mlp` with the block residual fused into the last
    kernel's epilogue: ``(gelu(x@w_in + b_in))@w_out + b_out + res`` in
    ONE pallas program — the final XLA elementwise add (and its extra
    HBM round trip of the [M, H] output) disappears.

    The residual is added AFTER the activation, in f32, then cast once
    to the output dtype — the same function the composition
    ``int8_gelu_mlp(...) + res`` computes, to within a ulp of float
    rounding (XLA may reassociate the outer add; under bf16 the fused
    form rounds once instead of twice).  VJP: the residual's cotangent
    is the incoming gradient
    unchanged (identity add), everything else is
    :func:`int8_gelu_mlp`'s backward verbatim.  Gated by
    :data:`FUSED_MLP_RESIDUAL` (default OFF — see the flag's note).
    """
    return _mlp_res_fwd(x, w_in, b_in, w_out, b_out, res)[0]


def _mlp_res_fwd(x, w_in, b_in, w_out, b_out, res):
    from .pallas.quant_matmul import quantize_cols, quantized_matmul
    interp = jax.default_backend() != "tpu"
    qwi, swi = quantize_cols(w_in)
    a, pre = quantized_matmul(x, qwi, swi, b_in, activation="gelu",
                              want_preact=True, block_m=256,
                              interpret=interp)
    qwo, swo = quantize_cols(w_out)
    y = quantized_matmul(a, qwo, swo, b_out, res, block_k=1024,
                         interpret=interp)
    return y, (x, pre, a, qwi, swi, qwo, swo)


def _mlp_res_bwd(res_tree, gy):
    # d(res) = gy (identity add); the rest is the shared MLP backward.
    dx, dw_in, db_in, dw_out, db_out = _mlp_bwd(res_tree, gy)
    return dx, dw_in, db_in, dw_out, db_out, gy


int8_gelu_mlp_res.defvjp(_mlp_res_fwd, _mlp_res_bwd)


class Int8Dense(nn.Module):
    """``nn.Dense`` with the matmul routed through :func:`int8_matmul`.

    Identical parameter tree ("kernel" f32 [in, features], optional
    "bias") and initializers, so bf16 and int8 runs share checkpoints.
    The kernel is re-quantized inside every step — its quantization error
    therefore tracks the CURRENT weights (no staleness), at the cost of
    an elementwise pass that is negligible next to the matmul.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, return_params: bool = False):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        bias = (self.param("bias", nn.initializers.zeros, (self.features,))
                if self.use_bias else None)
        if return_params:
            # Cross-layer fusion hook (int8_gelu_mlp spans two Dense
            # layers + the activation): hand the caller this layer's
            # params — created here so the tree stays IDENTICAL to the
            # unfused path — and let it run the fused computation.  ``x``
            # only supplies the input-feature count (an empty [0, K]
            # array works).
            return kernel, bias
        lead = x.shape[:-1]
        y = int8_matmul(x.reshape(-1, x.shape[-1]).astype(self.dtype),
                        kernel)
        y = y.reshape(*lead, self.features)
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y
