"""Int8 quantized TRAINING matmuls — opening the MXU's int8 rate (2x bf16).

The v5e MXU runs int8×int8→int32 at twice the bf16 FLOP rate (measured on
this chip: 343 TOPS pipelined vs 179 bf16 TFLOP/s at 8192³; 271 vs 162 at
the GPT MLP's own shapes — 1.7-1.9x).  The round-3 profile put 79.5% of
flagship-step device time in matmuls, so quantized training is the one
lever left on headline MFU (VERDICT r3 #2).  (The reference trained pure
float32 and had no quantization story at all, reference
``distributed.py:78-84``.)

Scheme — the SwitchBack recipe (per-row dynamic activation scales, int8
forward and input-gradient matmuls, full-precision weight-gradient
matmul):

- **forward**  ``y = (q(x)·q(w)) * sx * sw``: activations quantized
  per-ROW (each token its own scale), weights per-OUTPUT-CHANNEL — both
  scale vectors index non-contracted axes, so the int32 product is
  rescaled exactly.
- **dgrad** (int8): ``dx = (q(g)·q(wᵀ)) * sg * swᵀ`` — ``wᵀ`` is
  re-quantized per-column (the output axis of this product), again
  factorable.
- **wgrad** (bf16/f32): ``dw = xᵀ·g`` at full precision — the
  gradient-accumulation path is where int8 noise compounds into
  divergence, and it is 1/3 of the matmul FLOPs, so precision is kept
  where it matters (this is the error-compensation choice; the honest
  convergence delta is recorded by ``tests/test_int8_train.py`` and the
  bench's ``gpt_int8_*`` arm).

A FUSED pallas kernel exists (``..pallas.quant_matmul``: activations
quantized in the matmul prologue in VMEM — 264/322 TFLOP/s isolated at
the GPT MLP's shapes, 1.6-2x the bf16 matmul) but is NOT the in-step
default: measured in the full train step it LOSES to this XLA
formulation (fused fwd+dgrad 204.6 ms vs XLA 179.9 vs bf16 171.4; fused
fwd-only 182.1), because the opaque pallas call costs XLA its
bias/gelu-into-matmul epilogue fusions and adds layout conversions
around every call, and dgrad re-quantizes the transposed weight each
step.  Three engineered configurations, all measured, all behind bf16 on
this stack — set ``FUSED_KERNEL_IN_STEP = True`` to re-route fwd/dgrad
through the kernel when the composition costs change (e.g. in-kernel
bias+gelu epilogues, cached transposed weights — the recorded remaining
work).

:class:`Int8Dense` is a drop-in for ``flax.linen.Dense``: same parameter
names ("kernel"/"bias"), same initializers, same tree — checkpoints are
interchangeable with the bf16 model, so a run can switch precision on
restore.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


def _quant_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-ROW (last axis reduced): returns (q, scale)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / s), -127, 127).astype(jnp.int8)
    return q, s


# Per-COLUMN weight quantization: ONE definition, shared with the fused
# pallas kernel so the two paths can never drift apart (the equivalence
# tests assume identical weight quantization).
from .pallas.quant_matmul import quantize_cols as _quant_cols  # noqa: E402


def _i8_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """int8 [M, K] @ int8 [K, N] -> int32 [M, N] on the MXU's int8 path."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)


#: Route int8_matmul's fwd/dgrad through the pallas fused-quantize kernel
#: on TPU.  OFF by default: the kernel wins in isolation but loses in the
#: full step (see the module docstring's measurements) — the flag exists
#: so the trade re-measures in one line when the composition changes.
#: Read at TRACE time: set it BEFORE the train step first compiles (a
#: flip in a running process is masked by the jit cache — restart or
#: jax.clear_caches() to re-measure).
FUSED_KERNEL_IN_STEP = False


def _use_fused_kernel(M: int, K: int, N: int) -> bool:
    """Gate for the pallas fused-quantize kernel (compiled Mosaic, tileable
    shapes, and the module-level opt-in)."""
    if not FUSED_KERNEL_IN_STEP:
        return False
    from .pallas.quant_matmul import supported
    return jax.default_backend() == "tpu" and supported(M, K, N)


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x [M, K] @ w [K, N]`` with int8 forward/dgrad, f32 wgrad."""
    return _int8_fwd(x, w)[0]


def _fwd_math(x, w):
    M, K = x.shape
    N = w.shape[1]
    if _use_fused_kernel(M, K, N):
        from .pallas.quant_matmul import quantize_cols, quantized_matmul
        qw, sw = quantize_cols(w)
        return quantized_matmul(x, qw, sw)
    qx, sx = _quant_rows(x)
    qw, sw = _quant_cols(w)
    y = _i8_dot(qx, qw).astype(jnp.float32) * sx * sw
    return y.astype(x.dtype)


def _int8_fwd(x, w):
    return _fwd_math(x, w), (x, w)


def _int8_bwd(res, g):
    x, w = res
    M, N = g.shape
    K = w.shape[0]
    if _use_fused_kernel(M, N, K):
        from .pallas.quant_matmul import quantize_cols, quantized_matmul
        qwt, swt = quantize_cols(w.T)
        dx = quantized_matmul(g, qwt, swt).astype(x.dtype)
    else:
        qg, sg = _quant_rows(g)
        qwt, swt = _quant_cols(w.T)
        dx = (_i8_dot(qg, qwt).astype(jnp.float32) * sg * swt).astype(
            x.dtype)
    dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


int8_matmul.defvjp(_int8_fwd, _int8_bwd)


class Int8Dense(nn.Module):
    """``nn.Dense`` with the matmul routed through :func:`int8_matmul`.

    Identical parameter tree ("kernel" f32 [in, features], optional
    "bias") and initializers, so bf16 and int8 runs share checkpoints.
    The kernel is re-quantized inside every step — its quantization error
    therefore tracks the CURRENT weights (no staleness), at the cost of
    an elementwise pass that is negligible next to the matmul.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        lead = x.shape[:-1]
        y = int8_matmul(x.reshape(-1, x.shape[-1]).astype(self.dtype),
                        kernel)
        y = y.reshape(*lead, self.features)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            y = y + bias.astype(y.dtype)
        return y
