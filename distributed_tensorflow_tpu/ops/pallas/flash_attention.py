"""Flash attention as a Pallas TPU kernel — blockwise online-softmax in VMEM.

The reference has no attention op at all (its model is a 784→100→10 MLP,
reference ``distributed.py:65-87``); this kernel backs the framework's
transformer stack where XLA's fused attention is not enough: O(S) memory in
sequence length (no [S, S] score materialization in HBM), fp32 accumulation,
MXU-shaped block matmuls.

Layout/grid design (pallas_guide.md idioms):
- inputs [B, S, H, D] are viewed as [B*H, S, D]; grid = (B*H, S/bq, S/bk) with
  the K-block dimension innermost — TPU grids execute sequentially over the
  last dimension, so the VMEM scratch accumulators (m, l, acc) carry the
  running softmax state across K blocks of one (head, Q-block) pair;
- the output block is written once, on the last K step;
- scores/stats stay entirely in VMEM; fp32 throughout
  (``preferred_element_type``) regardless of input dtype.

Differentiation: the kernel is wrapped in ``jax.custom_vjp``.  The backward
pass is **blockwise pallas too** (FlashAttention-2 style): the forward saves
the per-row logsumexp alongside the output, and two kernels accumulate
dk/dv (grid over K blocks, scanning Q) and dq (grid over Q blocks, scanning
K) entirely in VMEM — O(S) HBM in sequence length end to end, no [S, S]
score materialization in either direction.  The only dense fallback is the
top-level one in :func:`flash_attention` (sequence length not divisible by
8), which routes the whole op — forward and backward — through the dense
XLA formulation.

On non-TPU backends the kernels run in interpreter mode, so CPU CI covers
them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
_LANE = 128


def _pick_block(s: int, preferred: int | None = None,
                window: int = 0) -> int:
    """Largest power-of-two divisor of ``s`` capped at ``preferred``.

    The default cap is SEQUENCE-DEPENDENT (measured on the v5e rig,
    causal bf16 fwd+bwd, D=128): 512 for short rows — at S=2048 it beats
    the kernels' original 128 by ~1.2-1.3x and 1024 is a wash (19.3 vs
    19.7 ms) — but 1024 for S >= 4096, where fewer grid steps carrying
    the online-softmax state win outright: 20.3 -> 19.1 ms at S=4096 and
    27.2 -> 23.7 ms (−13%) at S=8192 (r4 sweep).  The 1024² fp32 score
    block costs 4 MiB of VMEM, which compiles with margin on this
    generation.

    SLIDING-WINDOW kernels keep the 512 cap regardless of S: the band
    spans ``ceil((window-1)/block)+1`` K blocks, so a block wider than
    the window inflates the keys actually fetched (2x1024 vs 3x512 for
    window=1024) — the r4 sweep measured the 1024 block REGRESSING the
    windowed rows (S=32768 w=1024: 58.8 -> 60.6 ms) while winning the
    full-causal ones.
    """
    if preferred is None:
        preferred = 512 if window else (1024 if s >= 4096 else 512)
    b = 1
    while s % (b * 2) == 0 and b * 2 <= preferred:
        b *= 2
    return b


def _layout_ok(s: int) -> bool:
    """True when the [*, S] row arrays (mask/lse/delta) can be sliced per
    block on compiled Mosaic: single-block rows slice statically, multi-block
    rows need 128-lane-aligned offsets."""
    b = _pick_block(s)
    return b == s or b % _LANE == 0


def _band_nb(window: int, block: int) -> int:
    """K blocks a q block's sliding-window band spans (block_q == block_k):
    the range [q_lo - window + 1, q_lo + block - 1] covers the diagonal block
    plus ceil((window - 1) / block) older ones."""
    return (window + block - 2) // block + 1


def _row_slice(ref, i, block: int, n: int):
    """``ref[0, 0, i*block : i*block+block]`` with a STATIC offset when the
    grid dimension has a single step — Mosaic cannot prove alignment of a
    dynamic minor-dim offset even when i is identically zero."""
    if n == 1:
        return ref[0, 0, :block]
    return ref[0, 0, pl.ds(i * block, block)]


def _block_valid(logits_shape, mask_blk, *, causal, iq, ik, block_q, block_k,
                 q_offset=0, k_offset=0, window=0):
    """Validity mask for one [bq, bk] score block (padding + causal + window).

    ``q_offset``/``k_offset`` shift the causal position grid — 0 for the
    monolithic kernels, the chunk's (possibly dynamic) global position for
    the ring chunk kernels.  ``window`` > 0 (causal only) restricts each
    query to its ``window`` most recent keys: ``q_pos - k_pos < window``."""
    valid = jnp.ones(logits_shape, dtype=jnp.bool_)
    if mask_blk is not None:
        valid = valid & (mask_blk[None, :] != 0)
    if causal:
        q_pos = (q_offset + iq * block_q
                 + jax.lax.broadcasted_iota(jnp.int32, logits_shape, 0))
        k_pos = (k_offset + ik * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, logits_shape, 1))
        valid = valid & (q_pos >= k_pos)
        if window:
            valid = valid & (q_pos - k_pos < window)
    return valid


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, m_scr, l_scr,
            acc_scr, *, scale: float, causal: bool, block_q: int,
            block_k: int, nq: int, nkb: int, skip_empty: bool = False,
            window: int = 0, band: int = 0):
    iq = pl.program_id(1)
    nk = pl.num_programs(2)
    if band:
        # Banded grid (sliding window): the K dimension iterates only the
        # ``band`` blocks that can intersect this q block's window — grid
        # step j maps to true K block iq - (band-1) + j; the BlockSpec
        # index_map clips negatives to 0 (junk block, masked/skipped below).
        ik = iq - (band - 1) + pl.program_id(2)
    else:
        ik = pl.program_id(2)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
        k = k_ref[0].astype(jnp.float32)                  # [bk, D]
        logits = jax.lax.dot_general(                     # [bq, bk]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

        ik_c = jnp.clip(ik, 0, nkb - 1) if band else ik   # safe slicing
        mask_blk = (None if mask_ref is None
                    else _row_slice(mask_ref, ik_c, block_k, nkb))
        valid = _block_valid(logits.shape, mask_blk, causal=causal,
                             iq=iq, ik=ik,
                             block_q=block_q, block_k=block_k, window=window)
        if band:
            # Interpreter path computes out-of-range band steps (clipped junk
            # block) and masks them away; compiled TPU skips them entirely.
            valid = valid & (ik >= 0)
        logits = jnp.where(valid, logits, _NEG)

        m_prev = m_scr[:, :1]                             # [bq, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        # `valid` multiply kills exp(0)=1 rows while everything seen is masked.
        p = jnp.exp(logits - m_new) * valid.astype(jnp.float32)
        corr = jnp.exp(m_prev - m_new)                    # [bq, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(                         # [bq, D]
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if band and skip_empty:
        # Banded grid already restricts to the window; only the left edge's
        # clipped (negative-index) steps remain to skip.
        pl.when(ik >= 0)(_compute)
    elif skip_empty:
        # Causal: skip K blocks entirely above the diagonal — their every
        # element is masked, so running them is pure wasted MXU work (~2x at
        # large S).  With a sliding window (full grid), also skip blocks
        # entirely below the band.  Compiled TPU only: the CPU interpreter
        # can't lower a dynamic pl.when condition.
        cond = ik * block_k < (iq + 1) * block_q
        if window:
            cond &= (ik + 1) * block_k > iq * block_q - window + 1
        pl.when(cond)(_compute)
    else:
        _compute()

    @pl.when(pl.program_id(2) == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-30)          # fully-masked rows -> 0
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # Per-row logsumexp of the scaled scores: the backward pass
        # reconstitutes p = exp(s - L) from it blockwise.  Stored [BH, 1, S]
        # full-row (the mask-block trick: Mosaic wants the last two block
        # dims (8, 128)-tileable or whole-array); each Q block writes its
        # segment.
        if nq == 1:
            lse_ref[0, 0, :block_q] = m_scr[:, 0] + jnp.log(l[:, 0])
        else:
            lse_ref[0, 0, pl.ds(iq * block_q, block_q)] = (
                m_scr[:, 0] + jnp.log(l[:, 0]))


def _to_bh(x):
    """[B, S, H, D] -> [B*H, S, D]"""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_bh(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _mask_input(kv_mask):
    return kv_mask.astype(jnp.int32)[:, None, :]


def _mask_spec(S, H):
    # Mask is per-batch (not per-head): block row = bh // H.  The block spans
    # the full sequence — Mosaic tiling wants the minor block dim divisible by
    # 128 or equal to the array dim, and block_k is neither for short/odd S —
    # and the kernels slice their K/Q block out themselves.
    return pl.BlockSpec((1, 1, S), lambda bh, i, j, H=H: (bh // H, 0, 0),
                        memory_space=pltpu.VMEM)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


_warned: set = set()


def _warn_once(key: str, msg: str) -> None:
    if key not in _warned:
        _warned.add(key)
        import warnings
        warnings.warn(msg, stacklevel=3)


def _axis_env_names():
    """Named axes bound at trace time, or ``None`` when no probe works.

    The axis env has no stable public accessor; probe the known locations
    across JAX versions rather than silently reporting "not in shard_map"
    (which would force the dense fallback on multi-chip TPU forever)."""
    for probe in (lambda: __import__("jax._src.core", fromlist=["core"])
                  .get_axis_env().axis_names(),
                  lambda: jax.core.get_axis_env().axis_names()):  # moved alias
        try:
            return tuple(probe())
        except Exception:
            continue
    return None


def _inside_shard_map() -> bool:
    """True when tracing under shard_map (named axes bound): the kernel then
    sees per-device local arrays and lowers per-device."""
    names = _axis_env_names()
    if names is None:
        _warn_once(
            "axis-env-probe",
            "cannot detect shard_map context (JAX moved the axis-env API); "
            "assuming a GSPMD hazard — pallas kernels will fall back to "
            "dense XLA on multi-chip TPU. Report/update _axis_env_names().")
        return False
    return bool(names)


def _gspmd_hazard() -> bool:
    """Compiled Mosaic kernels cannot be auto-partitioned by GSPMD: under a
    multi-device jit *outside* shard_map the lowering raises.  (Interpreter
    mode lowers to plain partitionable HLO, so CPU CI is unaffected.)"""
    hazard = (jax.default_backend() == "tpu" and jax.device_count() > 1
              and not _inside_shard_map())
    if hazard:
        _warn_once(
            "gspmd-hazard",
            "pallas kernel requested under a multi-chip jit outside "
            "shard_map: GSPMD cannot partition Mosaic calls, using the "
            "dense XLA formulation instead (wrap the op in shard_map — "
            "e.g. the ring attention path — to keep pallas on multi-chip)")
    return hazard


def _flash_forward(q, k, v, kv_mask, *, causal: bool, window: int = 0):
    B, S, H, D = q.shape
    block_q = _pick_block(S, window=window)
    block_k = _pick_block(S, window=window)
    scale = 1.0 / float(D) ** 0.5

    qt, kt, vt = _to_bh(q), _to_bh(k), _to_bh(v)

    nq, nkb = S // block_q, S // block_k
    # Sliding window: restrict the K grid dimension to the blocks that can
    # intersect the band — the win over masking alone is that skipped
    # blocks are never even FETCHED into VMEM, so HBM traffic (the long-S
    # bottleneck) is O(S * window) too, not just the MXU work.
    band = 0
    if causal and window:
        nb = _band_nb(window, block_k)
        if nb < nkb:
            band = nb

    if band:
        grid = (B * H, nq, band)
        kv_idx = (lambda bh, iq, j, nb=band, hi=nkb - 1:
                  (bh, jnp.clip(iq - (nb - 1) + j, 0, hi), 0))
    else:
        grid = (B * H, nq, nkb)
        kv_idx = lambda bh, iq, ik: (bh, ik, 0)
    q_spec = pl.BlockSpec((1, block_q, D), lambda bh, iq, ik: (bh, iq, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, D), kv_idx,
                           memory_space=pltpu.VMEM)

    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qt, kt, vt]
    if kv_mask is not None:
        in_specs.append(_mask_spec(S, H))
        inputs.append(_mask_input(kv_mask))

    interpret = _interpret()
    opts = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
                nq=nq, nkb=nkb,
                skip_empty=causal and not interpret, window=window, band=band)
    kernel = functools.partial(_kernel, **opts)
    if kv_mask is None:
        kernel = _insert_none_mask(kernel, pos=3)

    out, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, 1, S), jnp.float32)],
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, block_q, D),
                                lambda bh, iq, ik: (bh, iq, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 1, S), lambda bh, iq, ik: (bh, 0, 0),
                                memory_space=pltpu.VMEM)],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running sum l
            pltpu.VMEM((block_q, D), jnp.float32),       # output accumulator
        ],
        interpret=interpret,
    )(*inputs)
    return _from_bh(out, B, H), lse


# ---------------------------------------------------------------------------
# Blockwise backward (FlashAttention-2): p is reconstituted from the saved
# logsumexp; dk/dv accumulate over Q blocks, dq over K blocks.

def _insert_none_mask(kernel, pos: int):
    """Adapt a mask-taking kernel to a call with no mask input: pallas passes
    refs positionally, so splice ``None`` in where ``mask_ref`` would be."""
    def wrapped(*refs):
        return kernel(*refs[:pos], None, *refs[pos:])
    return wrapped


def _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, *,
               scale, causal, block_q, block_k, iq, ik, nq, nkb,
               q_offset=0, k_offset=0, window=0):
    """Shared per-block math for one [bq, bk] tile; returns the 5-tuple
    ``(p, ds, do, q_scaled, k)`` (the fp32 block operands are reused by the
    callers' accumulation matmuls).

    ``q_offset``/``k_offset`` shift the causal position grid — 0 for the
    monolithic backward, the chunk's dynamic global position for the ring
    chunk kernels."""
    q = q_ref[0].astype(jnp.float32) * scale              # [bq, D]
    k = k_ref[0].astype(jnp.float32)                      # [bk, D]
    logits = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    ik_c = jnp.clip(ik, 0, nkb - 1)
    iq_c = jnp.clip(iq, 0, nq - 1)
    mask_blk = (None if mask_ref is None
                else _row_slice(mask_ref, ik_c, block_k, nkb))
    valid = _block_valid(logits.shape, mask_blk, causal=causal, iq=iq, ik=ik,
                         block_q=block_q, block_k=block_k,
                         q_offset=q_offset, k_offset=k_offset, window=window)
    # Banded grids hand in out-of-range block indices at the edges (their
    # BlockSpec clips the fetch; the interpreter computes-and-masks here,
    # compiled TPU skips the body via the callers' pl.when guard).
    valid = valid & (ik == ik_c) & (iq == iq_c)
    lse_blk = _row_slice(lse_ref, iq_c, block_q, nq)      # [bq]
    delta_blk = _row_slice(delta_ref, iq_c, block_q, nq)  # [bq]
    # Mask BEFORE the exp: a fully-masked row has L ~ _NEG, and a raw finite
    # logit minus that would overflow exp to inf (inf * 0 = NaN).  With the
    # where, masked entries give exp(_NEG - L) ∈ {0, 1}, and the valid
    # multiply zeroes the residue.
    logits = jnp.where(valid, logits, _NEG)
    p = jnp.exp(logits - lse_blk[:, None]) * valid.astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)                    # [bq, D]
    v = v_ref[0].astype(jnp.float32)                      # [bk, D]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_blk[:, None])                    # [bq, bk]
    return p, ds, do, q, k


def _causal_guard(compute, *, skip_empty, iq, ik, block_q, block_k,
                  window=0):
    """Skip [bq, bk] tiles entirely above the causal diagonal (all-masked:
    p and ds are identically zero there) — same ~2x MXU saving as the
    forward's guard — and, with a sliding window, tiles entirely below the
    band.  Compiled TPU only; the CPU interpreter can't lower a dynamic
    pl.when condition."""
    if skip_empty:
        cond = ik * block_k < (iq + 1) * block_q
        if window:
            cond &= (ik + 1) * block_k > iq * block_q - window + 1
        pl.when(cond)(compute)
    else:
        compute()


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                block_q, block_k, nq, nkb, skip_empty, window=0, band=0):
    ik = pl.program_id(1)
    if band:
        # Banded grid: K block ik receives gradients from q blocks
        # [ik, ik + band - 1] only (its window's queries); step j maps to
        # true q block ik + j, clipped by the BlockSpec at the top edge.
        iq = ik + pl.program_id(2)
    else:
        iq = pl.program_id(2)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        p, ds, do, q, _ = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            iq=iq, ik=ik, nq=nq, nkb=nkb, window=window)
        # dv += p^T do ; dk += ds^T (q*scale) (q was pre-scaled in _bwd_block)
        dv_scr[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if band and skip_empty:
        pl.when(iq <= nq - 1)(_compute)
    else:
        _causal_guard(_compute, skip_empty=skip_empty, iq=iq, ik=ik,
                      block_q=block_q, block_k=block_k, window=window)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _emit():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
               dq_ref, dq_scr, *, scale, causal, block_q, block_k, nq, nkb,
               skip_empty, window=0, band=0):
    iq = pl.program_id(1)
    nk = pl.num_programs(2)
    if band:
        ik = iq - (band - 1) + pl.program_id(2)
    else:
        ik = pl.program_id(2)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        _, ds, _, _, k = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            iq=iq, ik=ik, nq=nq, nkb=nkb, window=window)
        # dq += ds k * scale  (ds is the gradient wrt the SCALED logits, and
        # logits = scale * q k^T, so d/dq = scale * ds k).
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if band and skip_empty:
        pl.when(ik >= 0)(_compute)
    else:
        _causal_guard(_compute, skip_empty=skip_empty, iq=iq, ik=ik,
                      block_q=block_q, block_k=block_k, window=window)

    @pl.when(pl.program_id(2) == nk - 1)
    def _emit():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, kv_mask, o, lse, g, *, causal: bool,
                    window: int = 0):
    B, S, H, D = q.shape
    block_q = _pick_block(S, window=window)
    block_k = _pick_block(S, window=window)
    scale = 1.0 / float(D) ** 0.5

    qt, kt, vt = _to_bh(q), _to_bh(k), _to_bh(v)
    ot, dot_ = _to_bh(o), _to_bh(g)
    # delta_i = sum_d do_id * o_id — the softmax-jacobian row term.
    # [BH, 1, S] full-row layout, like lse (see _flash_forward).
    delta = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                    -1)[:, None, :]

    interpret = _interpret()
    nq, nkb = S // block_q, S // block_k
    band = 0
    if causal and window:
        nb = _band_nb(window, block_k)
        if nb < nkb:
            band = nb
    opts = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
                nq=nq, nkb=nkb,
                skip_empty=causal and not interpret, window=window, band=band)

    def build(kernel_fn, *, q_minor: bool):
        """in_specs/inputs/kernel shared by both backward calls.

        ``q_minor``: q blocks indexed by the innermost grid dim (the dk/dv
        call, grid (BH, nk, nq|band)); otherwise by the middle dim (the dq
        call, grid (BH, nq, nk|band)).  In band mode the innermost dim
        iterates only the window's blocks; its index_map derives the true
        block from the outer index and clips at the edges (the kernels skip
        or mask the clipped steps).
        """
        if band and q_minor:        # dkv: j -> q block ik + j
            q_idx = (lambda bh, i, j, hi=nq - 1:
                     (bh, jnp.clip(i + j, 0, hi), 0))
        elif band:                  # dq: j -> k block iq - (band-1) + j
            q_idx = lambda bh, i, j: (bh, i, 0)
        else:
            q_idx = ((lambda bh, i, j: (bh, j, 0)) if q_minor
                     else (lambda bh, i, j: (bh, i, 0)))
        if band and q_minor:
            k_idx = lambda bh, i, j: (bh, i, 0)
        elif band:
            k_idx = (lambda bh, i, j, nb=band, hi=nkb - 1:
                     (bh, jnp.clip(i - (nb - 1) + j, 0, hi), 0))
        else:
            k_idx = ((lambda bh, i, j: (bh, i, 0)) if q_minor
                     else (lambda bh, i, j: (bh, j, 0)))
        q_spec = pl.BlockSpec((1, block_q, D), q_idx,
                              memory_space=pltpu.VMEM)
        k_spec = pl.BlockSpec((1, block_k, D), k_idx,
                              memory_space=pltpu.VMEM)
        row_spec = pl.BlockSpec((1, 1, S), lambda bh, i, j: (bh, 0, 0),
                                memory_space=pltpu.VMEM)
        in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
        inputs = [qt, kt, vt, dot_, lse, delta]
        kernel = functools.partial(kernel_fn, **opts)
        if kv_mask is not None:
            in_specs.append(_mask_spec(S, H))
            inputs.append(_mask_input(kv_mask))
        else:
            kernel = _insert_none_mask(kernel, pos=6)
        return kernel, in_specs, inputs

    # dk/dv: grid (BH, nk, nq) — Q innermost, accumulated in VMEM scratch.
    kernel, in_specs, inputs = build(_dkv_kernel, q_minor=True)
    dk, dv = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, S, D), v.dtype)],
        grid=(B * H, nkb, band or nq),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, block_k, D),
                                lambda bh, ik, iq: (bh, ik, 0),
                                memory_space=pltpu.VMEM)] * 2,
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32)] * 2,
        interpret=interpret,
    )(*inputs)

    # dq: grid (BH, nq, nk) — K innermost.
    kernel, in_specs, inputs = build(_dq_kernel, q_minor=False)
    dq = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=(B * H, nq, band or nkb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bh, iq, ik: (bh, iq, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    return (_from_bh(dq, B, H), _from_bh(dk, B, H), _from_bh(dv, B, H))


# ---------------------------------------------------------------------------
# Chunked variant: fold ONE K/V chunk into running online-softmax state.
# This is the building block ring attention (parallel/ring.py) runs per hop:
# carry (m, l, acc) travels outside, so the [Sq, Sk] scores of each hop stay
# in VMEM blocks instead of materializing per-hop logits in HBM.

def _chunk_tile_guard(compute, offs_ref, *, skip_empty, iq, ik,
                      block_q, block_k, window=0):
    """Skip tiles entirely above the causal diagonal — and, with a sliding
    window, entirely below the band — with the chunk's dynamic global
    offsets folded in (scalar prefetch): a tile contributes iff its lowest
    q position can see its first k position.  Compiled TPU only (the
    interpreter can't lower a dynamic pl.when)."""
    if skip_empty:
        cond = (offs_ref[1] + ik * block_k
                < offs_ref[0] + (iq + 1) * block_q)
        if window:
            cond &= (offs_ref[1] + (ik + 1) * block_k
                     > offs_ref[0] + iq * block_q - window + 1)
        pl.when(cond)(compute)
    else:
        compute()


def _chunk_kernel(offs_ref, q_ref, k_ref, v_ref, mask_ref, m_in_ref, l_in_ref,
                  acc_in_ref, m_out_ref, l_out_ref, acc_out_ref,
                  m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                  nq, nkb, skip_empty, window=0):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        # Seed the scratch from the incoming running state (not neutral
        # values): the chunk continues an online softmax already in flight.
        m_scr[:] = jnp.broadcast_to(
            _row_slice(m_in_ref, iq, block_q, nq)[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(
            _row_slice(l_in_ref, iq, block_q, nq)[:, None], l_scr.shape)
        acc_scr[:] = acc_in_ref[0]

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        mask_blk = (None if mask_ref is None
                    else _row_slice(mask_ref, ik, block_k, nkb))
        # Global positions: the chunk's place in the ring is dynamic
        # (axis_index at runtime), so offsets arrive via scalar prefetch.
        valid = _block_valid(logits.shape, mask_blk, causal=causal,
                             iq=iq, ik=ik, block_q=block_q, block_k=block_k,
                             q_offset=offs_ref[0], k_offset=offs_ref[1],
                             window=window)
        logits = jnp.where(valid, logits, _NEG)

        m_prev = m_scr[:, :1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        p = jnp.exp(logits - m_new) * valid.astype(jnp.float32)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v_ref[0].astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    _chunk_tile_guard(_compute, offs_ref, skip_empty=skip_empty, iq=iq, ik=ik,
                      block_q=block_q, block_k=block_k, window=window)

    @pl.when(ik == nk - 1)
    def _emit():
        if nq == 1:
            m_out_ref[0, 0, :block_q] = m_scr[:, 0]
            l_out_ref[0, 0, :block_q] = l_scr[:, 0]
        else:
            m_out_ref[0, 0, pl.ds(iq * block_q, block_q)] = m_scr[:, 0]
            l_out_ref[0, 0, pl.ds(iq * block_q, block_q)] = l_scr[:, 0]
        acc_out_ref[0] = acc_scr[:]


def flash_attention_chunk(
    q: jax.Array,          # [B, Sq, H, D]
    k: jax.Array,          # [B, Sk, H, D]
    v: jax.Array,          # [B, Sk, H, D]
    kv_mask: jax.Array | None,   # [B, Sk]; nonzero = attend
    m: jax.Array,          # [B, H, Sq] fp32 running max
    l: jax.Array,          # [B, H, Sq] fp32 running sum
    acc: jax.Array,        # [B, H, Sq, D] fp32 running (pre-divide) output
    *,
    q_offset: jax.Array | int,   # global position of q[:, 0] (dynamic ok)
    k_offset: jax.Array | int,   # global position of k[:, 0] (dynamic ok)
    causal: bool = False,
    window: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fold one K/V chunk into ``(m, l, acc)``; returns the updated state.

    Finalize with ``acc / max(l, eps)`` after the last chunk.  Shapes follow
    ring attention's carry layout; offsets may be traced scalars (ring
    position is only known at runtime).
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = _pick_block(Sq)
    block_k = _pick_block(Sk)
    scale = 1.0 / float(D) ** 0.5

    qt = _to_bh(q)
    kt, vt = _to_bh(k), _to_bh(v)
    m3 = m.reshape(B * H, 1, Sq)
    l3 = l.reshape(B * H, 1, Sq)
    acct = acc.reshape(B * H, Sq, D)
    offs = jnp.asarray(
        jnp.stack([jnp.asarray(q_offset, jnp.int32),
                   jnp.asarray(k_offset, jnp.int32)]))

    q_spec = pl.BlockSpec((1, block_q, D), lambda bh, iq, ik, s: (bh, iq, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, D), lambda bh, iq, ik, s: (bh, ik, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, Sq), lambda bh, iq, ik, s: (bh, 0, 0),
                            memory_space=pltpu.VMEM)
    acc_spec = pl.BlockSpec((1, block_q, D), lambda bh, iq, ik, s: (bh, iq, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qt, kt, vt]
    kernel = functools.partial(_chunk_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               nq=Sq // block_q, nkb=Sk // block_k,
                               skip_empty=causal and not _interpret(),
                               window=window)
    if kv_mask is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, Sk), lambda bh, iq, ik, s, H=H: (bh // H, 0, 0),
            memory_space=pltpu.VMEM))
        inputs.append(_mask_input(kv_mask))
    else:
        kernel = _insert_none_mask(kernel, pos=4)  # after offs_ref + q/k/v
    in_specs += [row_spec, row_spec, acc_spec]
    inputs += [m3, l3, acct]

    m_o, l_o, acc_o = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * H, Sq // block_q, Sk // block_k),
            in_specs=in_specs,
            out_specs=[row_spec, row_spec, acc_spec],
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANE), jnp.float32),
                pltpu.VMEM((block_q, _LANE), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((B * H, 1, Sq), jnp.float32),
                   jax.ShapeDtypeStruct((B * H, 1, Sq), jnp.float32),
                   jax.ShapeDtypeStruct((B * H, Sq, D), jnp.float32)],
        interpret=_interpret(),
    )(offs, *inputs)
    return (m_o.reshape(B, H, Sq), l_o.reshape(B, H, Sq),
            acc_o.reshape(B, H, Sq, D))


def _chunk_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, mask_ref, dq_ref, dq_scr, *, scale, causal,
                     block_q, block_k, nq, nkb, skip_empty, window=0):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        _, ds, _, _, k = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            iq=iq, ik=ik, nq=nq, nkb=nkb,
            q_offset=offs_ref[0], k_offset=offs_ref[1], window=window)
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _chunk_tile_guard(_compute, offs_ref, skip_empty=skip_empty, iq=iq, ik=ik,
                      block_q=block_q, block_k=block_k, window=window)

    @pl.when(ik == nk - 1)
    def _emit():
        dq_ref[0] = dq_scr[:]


def _chunk_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, mask_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                      scale, causal, block_q, block_k, nq, nkb, skip_empty,
                      window=0):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        p, ds, do, q, _ = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            iq=iq, ik=ik, nq=nq, nkb=nkb,
            q_offset=offs_ref[0], k_offset=offs_ref[1], window=window)
        dv_scr[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    _chunk_tile_guard(_compute, offs_ref, skip_empty=skip_empty, iq=iq, ik=ik,
                      block_q=block_q, block_k=block_k, window=window)

    @pl.when(iq == nq - 1)
    def _emit():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]


def _chunk_bwd_call(kernel_fn, *, q, k, v, do, lse, delta, kv_mask,
                    q_offset, k_offset, causal, q_major, out_shapes,
                    out_specs_fn, scratch_shapes, window=0):
    """Shared driver for the two chunk backward kernels (ring hops)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = _pick_block(Sq)
    block_k = _pick_block(Sk)
    scale = 1.0 / float(D) ** 0.5

    qt, kt, vt, dot_ = _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(do)
    lse3 = lse.reshape(B * H, 1, Sq)
    delta3 = delta.reshape(B * H, 1, Sq)
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)])

    # q_major=True: grid (BH, nk, nq), q indexed by the innermost dim.
    q_idx = ((lambda bh, i, j, s: (bh, j, 0)) if q_major
             else (lambda bh, i, j, s: (bh, i, 0)))
    k_idx = ((lambda bh, i, j, s: (bh, i, 0)) if q_major
             else (lambda bh, i, j, s: (bh, j, 0)))
    q_spec = pl.BlockSpec((1, block_q, D), q_idx, memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, D), k_idx, memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, Sq), lambda bh, i, j, s: (bh, 0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
    inputs = [qt, kt, vt, dot_, lse3, delta3]
    kernel = functools.partial(kernel_fn, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               nq=Sq // block_q, nkb=Sk // block_k,
                               skip_empty=causal and not _interpret(),
                               window=window)
    if kv_mask is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, Sk), lambda bh, i, j, s, H=H: (bh // H, 0, 0),
            memory_space=pltpu.VMEM))
        inputs.append(_mask_input(kv_mask))
    else:
        kernel = _insert_none_mask(kernel, pos=7)  # offs + q/k/v/do/lse/delta
    grid = ((B * H, Sk // block_k, Sq // block_q) if q_major
            else (B * H, Sq // block_q, Sk // block_k))
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=grid, in_specs=in_specs,
            out_specs=out_specs_fn(block_q, block_k, D),
            scratch_shapes=scratch_shapes(block_q, block_k, D)),
        out_shape=out_shapes,
        interpret=_interpret(),
    )(offs, *inputs)


def flash_attention_chunk_dq(q, k, v, kv_mask, do, lse, delta, *,
                             q_offset, k_offset, causal=False, window=0):
    """dq partial for local q rows against ONE K/V chunk (fp32, [B,H,Sq,D] —
    the ring's accumulator layout; sum over chunks outside)."""
    B, Sq, H, D = q.shape
    out = _chunk_bwd_call(
        _chunk_dq_kernel, q=q, k=k, v=v, do=do, lse=lse, delta=delta,
        kv_mask=kv_mask, q_offset=q_offset, k_offset=k_offset, causal=causal,
        window=window, q_major=False,
        out_shapes=jax.ShapeDtypeStruct((B * H, Sq, D), jnp.float32),
        out_specs_fn=lambda bq, bk, D_: pl.BlockSpec(
            (1, bq, D_), lambda bh, i, j, s: (bh, i, 0),
            memory_space=pltpu.VMEM),
        scratch_shapes=lambda bq, bk, D_: [pltpu.VMEM((bq, D_), jnp.float32)])
    return out.reshape(B, H, Sq, D)


def flash_attention_chunk_dkv(q, k, v, kv_mask, do, lse, delta, *,
                              q_offset, k_offset, causal=False, window=0):
    """(dk, dv) partials for ONE K/V chunk from the local q rows (fp32,
    [B,H,Sk,D] — travels the ring with the chunk; sum over devices)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    dk, dv = _chunk_bwd_call(
        _chunk_dkv_kernel, q=q, k=k, v=v, do=do, lse=lse, delta=delta,
        kv_mask=kv_mask, q_offset=q_offset, k_offset=k_offset, causal=causal,
        window=window, q_major=True,
        out_shapes=[jax.ShapeDtypeStruct((B * H, Sk, D), jnp.float32)] * 2,
        out_specs_fn=lambda bq, bk, D_: [pl.BlockSpec(
            (1, bk, D_), lambda bh, i, j, s: (bh, i, 0),
            memory_space=pltpu.VMEM)] * 2,
        scratch_shapes=lambda bq, bk, D_: [
            pltpu.VMEM((bk, D_), jnp.float32)] * 2)
    return dk.reshape(B, H, Sk, D), dv.reshape(B, H, Sk, D)


def _dense_reference(q, k, v, kv_mask, *, causal: bool, window: int = 0):
    """fp32 dense attention — the fallback/rematerialization target.

    Delegates to the xla backend of :func:`..attention.dot_product_attention`
    (one definition of the masked-softmax semantics, not two to keep in sync).
    """
    from ..attention import dot_product_attention
    return dot_product_attention(q, k, v, kv_mask=kv_mask, causal=causal,
                                 window=window, backend="xla")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, kv_mask, causal, window):
    out, _ = _flash_forward(q, k, v, kv_mask, causal=causal, window=window)
    return out


def _flash_fwd(q, k, v, kv_mask, causal, window):
    out, lse = _flash_forward(q, k, v, kv_mask, causal=causal, window=window)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd(causal, window, residuals, g):
    q, k, v, kv_mask, o, lse = residuals
    dq, dk, dv = _flash_backward(q, k, v, kv_mask, o, lse, g, causal=causal,
                                 window=window)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,                        # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,    # [B, S]; nonzero = attend
    *,
    causal: bool = False,
    window: int = 0,
) -> jax.Array:
    """Blockwise flash attention; differentiable (blockwise pallas VJP).

    ``window`` > 0 (requires ``causal``) restricts each query to its
    ``window`` most recent keys (sliding-window attention); whole blocks
    outside the band are skipped, so compiled cost is O(S * window)."""
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if q.shape[1] % 8 or not _layout_ok(q.shape[1]):
        # No Mosaic-tileable block decomposition — dense is the better
        # program (and the only compilable one: multi-block rows need
        # 128-aligned block offsets for the mask/lse slices).
        return _dense_reference(q, k, v, kv_mask, causal=causal,
                                window=window)
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        # Interpreter mode is a CPU-CI affordance; on other accelerators it
        # would silently run orders of magnitude slow — dense XLA is the
        # right program there.
        return _dense_reference(q, k, v, kv_mask, causal=causal,
                                window=window)
    if _gspmd_hazard():
        # Multi-chip jit outside shard_map: GSPMD cannot partition the
        # Mosaic call — dense XLA partitions fine.  (The ring path wraps its
        # chunk kernels in shard_map and keeps pallas on multi-chip.)
        return _dense_reference(q, k, v, kv_mask, causal=causal,
                                window=window)
    return _flash(q, k, v, kv_mask, causal, window)
