"""Flash attention as a Pallas TPU kernel — blockwise online-softmax in VMEM.

The reference has no attention op at all (its model is a 784→100→10 MLP,
reference ``distributed.py:65-87``); this kernel backs the framework's
transformer stack where XLA's fused attention is not enough: O(S) memory in
sequence length (no [S, S] score materialization in HBM), fp32 accumulation,
MXU-shaped block matmuls.

Layout/grid design (pallas_guide.md idioms):
- inputs [B, S, H, D] are viewed as [B*H, S, D]; grid = (B*H, S/bq, S/bk) with
  the K-block dimension innermost — TPU grids execute sequentially over the
  last dimension, so the VMEM scratch accumulators (m, l, acc) carry the
  running softmax state across K blocks of one (head, Q-block) pair;
- the output block is written once, on the last K step;
- scores/stats stay entirely in VMEM; fp32 throughout
  (``preferred_element_type``) regardless of input dtype.

Differentiation: the kernel is wrapped in ``jax.custom_vjp``.  The backward
pass recomputes attention with the dense XLA formulation (flash-style
rematerialization: nothing but q/k/v/mask is saved between fwd and bwd); a
blockwise pallas backward is a further optimization, not a semantics change.

On non-TPU backends the kernel runs in interpreter mode, so CPU CI covers it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
_LANE = 128


def _pick_block(s: int, preferred: int = 128) -> int:
    """Largest power-of-two divisor of ``s`` capped at ``preferred``."""
    b = 1
    while s % (b * 2) == 0 and b * 2 <= preferred:
        b *= 2
    return b


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, m_scr, l_scr, acc_scr,
            *, scale: float, causal: bool, block_q: int, block_k: int,
            skip_empty: bool = False):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
        k = k_ref[0].astype(jnp.float32)                  # [bk, D]
        logits = jax.lax.dot_general(                     # [bq, bk]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

        valid = jnp.ones_like(logits, dtype=jnp.bool_)
        if mask_ref is not None:
            # mask_ref block is [1, 1, S] (full sequence; see _flash_forward);
            # slice this K block out dynamically.
            mask_blk = mask_ref[0, 0, pl.ds(ik * block_k, block_k)]
            valid = valid & (mask_blk[None, :] != 0)
        if causal:
            iq = pl.program_id(1)
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = valid & (q_pos >= k_pos)
        logits = jnp.where(valid, logits, _NEG)

        m_prev = m_scr[:, :1]                             # [bq, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        # `valid` multiply kills exp(0)=1 rows while everything seen is masked.
        p = jnp.exp(logits - m_new) * valid.astype(jnp.float32)
        corr = jnp.exp(m_prev - m_new)                    # [bq, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(                         # [bq, D]
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if skip_empty:
        # Causal: skip K blocks entirely above the diagonal — their every
        # element is masked, so running them is pure wasted MXU work (~2x at
        # large S).  Compiled TPU only: the CPU interpreter can't lower a
        # dynamic pl.when condition.
        iq = pl.program_id(1)
        pl.when(ik * block_k < (iq + 1) * block_q)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-30)          # fully-masked rows -> 0
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, kv_mask, *, causal: bool):
    B, S, H, D = q.shape
    block_q = _pick_block(S)
    block_k = _pick_block(S)
    scale = 1.0 / float(D) ** 0.5

    # [B, S, H, D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    grid = (B * H, S // block_q, S // block_k)
    q_spec = pl.BlockSpec((1, block_q, D), lambda bh, iq, ik: (bh, iq, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, D), lambda bh, iq, ik: (bh, ik, 0),
                           memory_space=pltpu.VMEM)

    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qt, kt, vt]
    if kv_mask is not None:
        # Mask is per-batch (not per-head): block row = bh // H.  The block
        # spans the full sequence — Mosaic tiling wants the minor block dim
        # divisible by 128 or equal to the array dim, and block_k is neither
        # for short/odd S — and the kernel slices out its K block itself.
        in_specs.append(pl.BlockSpec(
            (1, 1, S), lambda bh, iq, ik, H=H: (bh // H, 0, 0),
            memory_space=pltpu.VMEM))
        inputs.append(kv_mask.astype(jnp.int32)[:, None, :])

    interpret = jax.default_backend() != "tpu"
    opts = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
                skip_empty=causal and not interpret)
    if kv_mask is None:
        def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
            _kernel(q_ref, k_ref, v_ref, None, o_ref, m_scr, l_scr, acc_scr,
                    **opts)
    else:
        kernel = functools.partial(_kernel, **opts)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bh, iq, ik: (bh, iq, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running sum l
            pltpu.VMEM((block_q, D), jnp.float32),       # output accumulator
        ],
        interpret=interpret,
    )(*inputs)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _dense_reference(q, k, v, kv_mask, *, causal: bool):
    """fp32 dense attention — the backward-pass rematerialization target.

    Delegates to the xla backend of :func:`..attention.dot_product_attention`
    (one definition of the masked-softmax semantics, not two to keep in sync).
    """
    from ..attention import dot_product_attention
    return dot_product_attention(q, k, v, kv_mask=kv_mask, causal=causal,
                                 backend="xla")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, kv_mask, causal):
    return _flash_forward(q, k, v, kv_mask, causal=causal)


def _flash_fwd(q, k, v, kv_mask, causal):
    return _flash_forward(q, k, v, kv_mask, causal=causal), (q, k, v, kv_mask)


def _flash_bwd(causal, residuals, g):
    q, k, v, kv_mask = residuals
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, kv_mask, causal=causal),
        q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,                        # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,    # [B, S]; nonzero = attend
    *,
    causal: bool = False,
) -> jax.Array:
    """Blockwise flash attention; differentiable (rematerializing VJP)."""
    if q.shape[1] % 8:
        # No clean block decomposition — the dense path is the better program.
        return _dense_reference(q, k, v, kv_mask, causal=causal)
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        # Interpreter mode is a CPU-CI affordance; on other accelerators it
        # would silently run orders of magnitude slow — dense XLA is the
        # right program there.
        return _dense_reference(q, k, v, kv_mask, causal=causal)
    return _flash(q, k, v, kv_mask, causal)
