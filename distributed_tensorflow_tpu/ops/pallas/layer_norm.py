"""Fused LayerNorm as a Pallas TPU kernel — one VMEM pass over the rows.

The reference has no normalization op (its model is a 784→100→10 MLP,
reference ``distributed.py:65-87``); this kernel backs the framework's
transformer stack.  LayerNorm is HBM-bandwidth-bound: the win is reading each
activation row exactly once — mean, variance, normalize, scale and shift fused
in VMEM with fp32 statistics — instead of letting separate reductions and the
elementwise tail make extra passes.  XLA usually fuses this well on its own;
the kernel exists for the cases where it doesn't (odd fusion boundaries around
collectives/remat) and is flag-selectable (``--fused_layer_norm``), never the
silent default.

Differentiation follows the flash-attention pattern (``flash_attention.py``):
``jax.custom_vjp`` with a rematerializing backward — the backward pass
re-derives gradients through the dense XLA formulation so there is exactly one
definition of the semantics.  On non-TPU backends the kernel runs in
interpreter mode, so CPU CI covers the real kernel body.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_block(n: int, preferred: int = 256) -> int:
    """Largest power-of-two divisor of ``n`` capped at ``preferred``."""
    b = 1
    while n % (b * 2) == 0 and b * 2 <= preferred:
        b *= 2
    return b


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)                    # [br, H]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    y = centered * jax.lax.rsqrt(var + eps)
    o_ref[...] = y * g_ref[...] + b_ref[...]


def _dense_reference(x, scale, bias, eps: float):
    """fp32 LayerNorm, the backward-pass rematerialization target."""
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    return centered * jax.lax.rsqrt(var + eps) * scale + bias


def _ln_forward(x, scale, bias, eps: float):
    orig_shape = x.shape
    H = orig_shape[-1]
    rows = 1
    for dim in orig_shape[:-1]:
        rows *= dim
    xr = x.reshape(rows, H)
    block_r = _pick_block(rows)

    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, H), jnp.float32),
        grid=(rows // block_r,),
        in_specs=[
            pl.BlockSpec((block_r, H), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            # scale/bias: one full [1, H] vector, same block for every row tile
            # (H as the full minor dim keeps Mosaic's lane tiling happy for
            # arbitrary H, as with the flash kernel's mask block).
            pl.BlockSpec((1, H), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_r, H), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=jax.default_backend() != "tpu",
    )(xr, scale.astype(jnp.float32).reshape(1, H),
      bias.astype(jnp.float32).reshape(1, H))
    return out.reshape(orig_shape[:-1] + (H,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x, scale, bias, eps):
    return _ln_forward(x, scale, bias, eps)


def _fused_ln_fwd(x, scale, bias, eps):
    return _ln_forward(x, scale, bias, eps), (x, scale, bias)


def _fused_ln_bwd(eps, residuals, g):
    x, scale, bias = residuals
    _, vjp = jax.vjp(
        lambda x, s, b: _dense_reference(x, s, b, eps), x, scale, bias)
    return vjp(g)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(
    x: jax.Array,                 # [..., H]
    scale: jax.Array,             # [H]
    bias: jax.Array,              # [H]
    *,
    eps: float = 1e-6,
) -> jax.Array:
    """Fused LayerNorm over the last axis; fp32 output (matching the models'
    ``nn.LayerNorm(dtype=jnp.float32)`` convention); differentiable."""
    from .flash_attention import _gspmd_hazard

    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        # Interpreter mode is a CPU-CI affordance; elsewhere dense XLA is the
        # right program.
        return _dense_reference(x, scale, bias, eps)
    if _gspmd_hazard():
        # Multi-chip jit outside shard_map: GSPMD cannot partition the
        # Mosaic call — dense XLA (which fuses LN well anyway) partitions
        # fine.
        return _dense_reference(x, scale, bias, eps)
    return _fused_ln(x, scale, bias, eps)


import flax.linen as nn  # noqa: E402  (import after jax/pallas: cheap, optional)


class FusedLayerNorm(nn.Module):
    """Drop-in for ``nn.LayerNorm(dtype=jnp.float32)``: identical parameter
    names/shapes ("scale"/"bias", [H], fp32), so checkpoints written with
    either implementation restore into the other."""

    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        H = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (H,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (H,), jnp.float32)
        return fused_layer_norm(x, scale, bias, eps=self.epsilon)


def make_layer_norm(fused: bool, name: str | None = None) -> nn.Module:
    """The models' single LN factory: fp32 LayerNorm, fused (pallas) or stock
    — identical math and parameter tree either way."""
    if fused:
        return FusedLayerNorm(name=name)
    return nn.LayerNorm(dtype=jnp.float32, name=name)
