"""Fused quantize-and-matmul pallas kernel — the MXU's int8 rate without
the XLA-composition tax.

The XLA-composed int8 training path (``ops/quant_train.int8_matmul``'s
fallback) materializes an int8 copy of the activations in HBM and pays
layout copies around the int8 dot — measured +24 ms/step on the flagship
GPT, more than the int8 MXU saving (r4 ``gpt_int8_note``).  This kernel
quantizes each activation block IN THE MATMUL PROLOGUE, in VMEM: the
activations stream in as bf16 exactly once, the int8 copy never exists in
HBM, and the int32 partial products are rescaled per (row, K-block) as
they accumulate.

Measured on the v5e (device time via ``utils/xplane``, blocks 512/2048/512):

- M=8192 K=2048 N=8192 (GPT MLP in):  **264 TFLOP/s** — 1.6x the 162 the
  bf16 XLA matmul reaches at the same shapes;
- M=8192 K=8192 N=2048 (GPT MLP out): **322 TFLOP/s** — ~2x.

Scheme: weights are pre-quantized per OUTPUT COLUMN outside the kernel
(``quantize_cols`` — one elementwise pass per step, amortized over the M
rows); activations get per-(row, K-block) scales inside the kernel —
FINER than the per-row scales of the XLA path, so accuracy is equal or
better.  Exactness of the rescale: with per-column weight scales constant
across K-blocks, ``sum_kb (qx·qw) * sx_kb * sw == (sum_kb (qx·qw) * sx_kb)
* sw`` — both scale vectors index non-contracted axes of each partial
product.

The grid iterates K innermost with a VMEM f32 accumulator (TPU grids are
sequential, so the running block sum is race-free); the output block is
written once on the last K step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def quantize_cols(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-COLUMN (axis 0 reduced): ``w ≈ q * s``,
    ``q`` int8 [K, N], ``s`` f32 [1, N]."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=0, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return q, s


def _qmm_kernel(x_ref, w_ref, sw_ref, o_ref, acc_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    sx = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xb / sx), -127, 127).astype(jnp.int8)
    part = jax.lax.dot_general(q, w_ref[...], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)
    acc_ref[...] += part.astype(jnp.float32) * sx

    @pl.when(k == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * sw_ref[...]).astype(o_ref.dtype)


def _pick(dim: int, preferred: int) -> int:
    """Largest power-of-two divisor of ``dim`` capped at ``preferred``."""
    b = 1
    while dim % (b * 2) == 0 and b * 2 <= preferred:
        b *= 2
    return b


def supported(M: int, K: int, N: int) -> bool:
    """True when the kernel's tiling fits these dims (everything must
    split into >=128-wide power-of-two blocks for the MXU/lane layout);
    callers fall back to the XLA formulation otherwise."""
    return all(_pick(d, 512) >= 128 for d in (M, K, N))


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "block_k", "interpret"))
def quantized_matmul(x: jax.Array, qw: jax.Array, sw: jax.Array, *,
                     block_m: int = 512, block_n: int = 2048,
                     block_k: int = 512,
                     interpret: bool = False) -> jax.Array:
    """``x [M, K] (bf16/f32) @ (qw [K, N] int8 * sw [1, N])`` -> x.dtype.

    Activations are quantized per (row, K-block) inside the kernel; see
    the module docstring.  Block sizes clamp to the largest power-of-two
    divisors of the respective dims (use :func:`supported` to gate).
    ``interpret=True`` runs the same kernel under the pallas interpreter
    (CPU CI).
    """
    M, K = x.shape
    K2, N = qw.shape
    if K != K2 or sw.shape != (1, N):
        raise ValueError(f"shape mismatch: x {x.shape}, qw {qw.shape}, "
                         f"sw {sw.shape}")
    bm, bn, bk = _pick(M, block_m), _pick(N, block_n), _pick(K, block_k)
    return pl.pallas_call(
        _qmm_kernel,
        grid=(M // bm, N // bn, K // bk),
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                  pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
                  pl.BlockSpec((1, bn), lambda i, j, k: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, qw, sw)
