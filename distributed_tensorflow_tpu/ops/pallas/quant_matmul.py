"""Fused quantize-and-matmul pallas kernels — the MXU's int8 rate without
the XLA-composition tax.

The XLA-composed int8 training path (``ops/quant_train.int8_matmul``'s
fallback) materializes an int8 copy of the activations in HBM and pays
layout copies around the int8 dot — measured +24 ms/step on the flagship
GPT, more than the int8 MXU saving (r4 ``gpt_int8_note``).  These kernels
quantize each activation block IN THE MATMUL PROLOGUE, in VMEM: the
activations stream in as bf16 exactly once, the int8 copy never exists in
HBM, and the int32 partial products are rescaled per (row, K-block) as
they accumulate.  The MLP's remaining elementwise work rides along:

- :func:`quantized_matmul` — forward, with bias + gelu in the EPILOGUE,
  an optional pre-activation side output (the backward's residual), and
  an optional post-activation residual ADD (the transformer block's
  ``x + mlp(x)`` folded into the same HBM write — off by default, see
  the ``residual`` docs);
- :func:`quantized_matmul_nt` — backward (dgrad), reusing the FORWARD's
  quantized weight in its fwd layout: the weight's per-column scale
  indexes the contracted axis, so it folds into the incoming gradient
  before ITS quantization (``Σ_n g_n·qw_kn·s_n = Σ_n (g_n s_n)·qw_kn``),
  and the backward needs no weight re-quantization and no transpose —
  the two per-step composition taxes that kept r4's versions behind
  bf16.  The gelu backward runs in its prologue;
- :func:`quantized_matmul_dgelu` — the TN dgrad against an explicitly
  re-quantized ``w.T`` (pre-NT formulation, kept tested).

In-step result (flagship GPT, L=8 H=2048 I=8192 B=8 S=1024, A/B
best-of-2): **1.017x over bf16 end-to-end** via
``ops/quant_train.int8_gelu_mlp``, vs 0.84x for the r4 naive composition
— the full experiment ladder, including the variants that LOST, is in
BASELINE.md's int8 section.

Scheme: weights are pre-quantized per OUTPUT COLUMN outside the kernel
(``quantize_cols`` — one elementwise pass per step, amortized over the M
rows); activations get per-(row, K-block) scales inside the kernel —
FINER than the per-row scales of the XLA path, so accuracy is equal or
better.  Exactness of the rescale: with per-column weight scales constant
across K-blocks, ``sum_kb (qx·qw) * sx_kb * sw == (sum_kb (qx·qw) * sx_kb)
* sw`` — both scale vectors index non-contracted axes of each partial
product.

The grid iterates K innermost with a VMEM f32 accumulator (TPU grids are
sequential, so the running block sum is race-free); the output block is
written once on the last K step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def quantize_cols(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-COLUMN (axis 0 reduced): ``w ≈ q * s``,
    ``q`` int8 [K, N], ``s`` f32 [1, N]."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=0, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return q, s


# Tanh-approximation gelu and its derivative, in f32, matching
# jax.nn.gelu(approximate=True) — the form flax's nn.gelu applies, so the
# fused epilogue is numerically the same function the unfused model runs.
_GELU_C = 0.7978845608028654  # sqrt(2/pi)
_GELU_A = 0.044715


def _gelu(y):
    return 0.5 * y * (1.0 + jnp.tanh(_GELU_C * (y + _GELU_A * y * y * y)))


def _dgelu(y):
    t = jnp.tanh(_GELU_C * (y + _GELU_A * y * y * y))
    dt = (1.0 - t * t) * _GELU_C * (1.0 + 3.0 * _GELU_A * y * y)
    return 0.5 * (1.0 + t) + 0.5 * y * dt


def _quant_block(xb):
    """Per-(row, K-block) symmetric int8 of an f32 block: (q, scale)."""
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    sx = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xb / sx), -127, 127).astype(jnp.int8)
    return q, sx


def _qmm_kernel(*refs, activation=None, has_bias=False,
                want_preact=False, has_residual=False):
    """Quantize-matmul with the MLP epilogue fused in.

    Ref layout: x, w, sw, [bias], [residual], out, [preact],
    acc-scratch.  The epilogue (bias add, gelu, pre-activation emit,
    residual add) runs ON THE LAST K-STEP while the output block is
    still in VMEM — this is the work XLA loses the moment the matmul
    becomes an opaque pallas call (r4 ``gpt_int8_note``: forfeited
    bias/gelu fusions + layout copies cost more than the int8 MXU rate
    saved).  The residual rides LAST, after the activation — the
    transformer block's ``x + mlp(x)`` — so the stored pre-activation
    (the backward's input) is untouched by it.
    """
    it = iter(refs)
    x_ref, w_ref, sw_ref = next(it), next(it), next(it)
    b_ref = next(it) if has_bias else None
    r_ref = next(it) if has_residual else None
    o_ref = next(it)
    pre_ref = next(it) if want_preact else None
    acc_ref = next(it)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q, sx = _quant_block(x_ref[...].astype(jnp.float32))
    part = jax.lax.dot_general(q, w_ref[...], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)
    acc_ref[...] += part.astype(jnp.float32) * sx

    @pl.when(k == pl.num_programs(2) - 1)
    def _emit():
        y = acc_ref[...] * sw_ref[...]
        if has_bias:
            y = y + b_ref[...]
        if want_preact:
            # Round-trip through the storage dtype BEFORE the activation:
            # the backward recomputes gelu'(preact) from the stored copy,
            # and fwd/bwd must see the same function input.  (An all-bf16
            # epilogue was tried and measured NO faster — mosaic upcasts
            # the tanh path anyway — so the math stays f32.)
            pre = y.astype(pre_ref.dtype)
            pre_ref[...] = pre
            y = pre.astype(jnp.float32)
        if activation == "gelu":
            y = _gelu(y)
        if has_residual:
            y = y + r_ref[...].astype(jnp.float32)
        o_ref[...] = y.astype(o_ref.dtype)


def _qmm_dgelu_kernel(da_ref, pre_ref, w_ref, sw_ref, o_ref, *rest,
                      want_g=False):
    """Dgrad with the gelu-backward PROLOGUE fused in.

    Computes ``g = da * gelu'(pre)`` blockwise in VMEM, quantizes it per
    (row, K-block), and accumulates ``g @ qwt`` — the elementwise gelu
    backward never materializes in HBM unless ``want_g`` asks for it
    (the wgrad/bias-grad path does; it is written once, on the last
    output-column pass, straight from VMEM).  This is the pre-NT
    formulation kept for a re-quantized-weight dgrad; the MLP's default
    backward is :func:`quantized_matmul_nt`.
    """
    g_ref = rest[0] if want_g else None
    acc_ref = rest[-1]
    j, k = pl.program_id(1), pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    g = (da_ref[...].astype(jnp.float32)
         * _dgelu(pre_ref[...].astype(jnp.float32)))
    if want_g:
        # Last j-visit: pallas flushes an output block after its final
        # grid visit, so the write must land there (an early write then
        # unwritten revisits would flush a stale buffer).
        @pl.when(j == pl.num_programs(1) - 1)
        def _emit_g():
            g_ref[...] = g.astype(g_ref.dtype)
    q, sg = _quant_block(g)
    part = jax.lax.dot_general(q, w_ref[...], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)
    acc_ref[...] += part.astype(jnp.float32) * sg

    @pl.when(k == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * sw_ref[...]).astype(o_ref.dtype)


def _qmm_nt_kernel(*refs, prologue="fold", want_g=False):
    """NT dgrad: ``(da [* gelu'(pre)] * sw) @ qwᶠʷᵈ`` contracted on the
    weight's LAST axis — the backward reuses the FORWARD's quantized
    weight, read in its fwd layout.

    The algebra: ``dx_k = Σ_n g_n·w_nk`` with ``w_nk = qw_kn·s_n`` becomes
    ``Σ_n (g_n·s_n)·qw_kn`` — the per-column forward scale folds into the
    gradient BEFORE its quantization (it indexes the contracted axis, so
    it cannot ride the output like the fwd's scales).  Net effect: the
    backward needs NO weight re-quantization and NO transpose — the two
    remaining per-step composition taxes of the r4 finding.

    Ref layout: da, [pre], qw [N, K] (fwd layout), sf [1, K] (fwd col
    scales, folded in the prologue), out, [g], acc.  ``prologue``:
    "fold" (plain dgrad) or "dgelu_fold" (mlp_in dgrad, multiplies
    ``gelu'(pre)`` too).  ``want_g`` emits the UNFOLDED elementwise
    gradient ``da * gelu'(pre)`` for the wgrad path.
    """
    it = iter(refs)
    da_ref = next(it)
    pre_ref = next(it) if prologue == "dgelu_fold" else None
    w_ref, sf_ref = next(it), next(it)
    o_ref = next(it)
    g_ref = next(it) if want_g else None
    acc_ref = next(it)
    j, k = pl.program_id(1), pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    g = da_ref[...].astype(jnp.float32)
    if prologue == "dgelu_fold":
        g = g * _dgelu(pre_ref[...].astype(jnp.float32))
        if want_g:
            @pl.when(j == pl.num_programs(1) - 1)
            def _emit_g():
                g_ref[...] = g.astype(g_ref.dtype)
    q, sg = _quant_block(g * sf_ref[...])
    part = jax.lax.dot_general(q, w_ref[...], (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.int32)
    acc_ref[...] += part.astype(jnp.float32) * sg

    @pl.when(k == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("prologue", "want_g",
                                             "block_m", "block_n",
                                             "block_k", "interpret"))
def quantized_matmul_nt(da: jax.Array, qw: jax.Array, sw: jax.Array,
                        pre: jax.Array | None = None, *,
                        prologue: str = "fold", want_g: bool = False,
                        block_m: int = 512, block_n: int = 2048,
                        block_k: int = 512, interpret: bool = False):
    """Backward (dgrad) matmul against the FORWARD's quantized weight.

    ``da [M, K]`` (cotangent), ``qw [N, K]``/``sw [1, K]`` — the
    untouched outputs of the forward's :func:`quantize_cols` (``qw`` in
    fwd orientation; the kernel contracts its LAST axis) — returns
    ``dx [M, N] ≈ da @ (qw*sw).T`` in ``da.dtype``.  See
    :func:`_qmm_nt_kernel` for the scale-folding algebra and prologue
    modes; ``want_g`` (with ``prologue="dgelu_fold"``) also returns the
    elementwise gradient for the wgrad path.
    """
    if prologue not in ("fold", "dgelu_fold"):
        raise ValueError(f"unknown prologue {prologue!r}")
    if want_g and prologue != "dgelu_fold":
        raise ValueError("want_g only applies to the dgelu_fold prologue")
    M, K = da.shape
    N, K2 = qw.shape
    if K != K2 or sw.shape != (1, K):
        raise ValueError(f"shape mismatch: da {da.shape}, qw {qw.shape}, "
                         f"sw {sw.shape}")
    if pre is not None and pre.shape != (M, K):
        raise ValueError(f"pre shape {pre.shape} != da shape {da.shape}")
    if (pre is None) != (prologue == "fold"):
        raise ValueError("pre must be given exactly for dgelu_fold")
    bm, bn, bk = _pick(M, block_m), _pick(N, block_n), _pick(K, block_k)
    in_specs = [pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))]
    operands = [da]
    if pre is not None:
        in_specs.append(pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)))
        operands.append(pre)
    in_specs += [pl.BlockSpec((bn, bk), lambda i, j, k: (j, k)),
                 pl.BlockSpec((1, bk), lambda i, j, k: (0, k))]
    operands += [qw, sw]
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]
    out_shape = [jax.ShapeDtypeStruct((M, N), da.dtype)]
    if want_g:
        out_specs.append(pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)))
        out_shape.append(jax.ShapeDtypeStruct((M, K), da.dtype))
    out = pl.pallas_call(
        functools.partial(_qmm_nt_kernel, prologue=prologue,
                          want_g=want_g),
        grid=(M // bm, N // bn, K // bk),
        in_specs=in_specs,
        out_specs=out_specs if want_g else out_specs[0],
        out_shape=out_shape if want_g else out_shape[0],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(*operands)
    return out


def _pick(dim: int, preferred: int) -> int:
    """Largest power-of-two divisor of ``dim`` capped at ``preferred``."""
    b = 1
    while dim % (b * 2) == 0 and b * 2 <= preferred:
        b *= 2
    return b


def supported(M: int, K: int, N: int) -> bool:
    """True when the kernel's tiling fits these dims (everything must
    split into >=128-wide power-of-two blocks for the MXU/lane layout);
    callers fall back to the XLA formulation otherwise."""
    return all(_pick(d, 512) >= 128 for d in (M, K, N))


@functools.partial(jax.jit, static_argnames=("activation", "want_preact",
                                             "block_m", "block_n",
                                             "block_k", "interpret"))
def quantized_matmul(x: jax.Array, qw: jax.Array, sw: jax.Array,
                     bias: jax.Array | None = None,
                     residual: jax.Array | None = None, *,
                     activation: str | None = None,
                     want_preact: bool = False,
                     block_m: int = 512, block_n: int = 2048,
                     block_k: int = 512,
                     interpret: bool = False):
    """``x [M, K] (bf16/f32) @ (qw [K, N] int8 * sw [1, N])`` -> x.dtype.

    Activations are quantized per (row, K-block) inside the kernel; see
    the module docstring.  Block sizes clamp to the largest power-of-two
    divisors of the respective dims (use :func:`supported` to gate).
    ``interpret=True`` runs the same kernel under the pallas interpreter
    (CPU CI).

    Fused epilogue: ``bias`` ([N] or [1, N], f32) is added and
    ``activation`` ("gelu") applied to the output block in VMEM before
    the single HBM write.  ``want_preact`` (requires an activation) also
    emits the pre-activation tensor — the residual the backward needs —
    making the return ``(y, preact)``.  ``residual`` ([M, N]) is added
    LAST, after the activation — the transformer block's ``x + mlp(x)``
    fused into the same HBM write (gated by
    ``ops/quant_train.FUSED_MLP_RESIDUAL``: at the flagship shapes the
    extra input block measured 7 ms/step SLOWER than the XLA add, so the
    default composition keeps the add outside; the fused form exists so
    that trade re-measures in one line).
    """
    M, K = x.shape
    K2, N = qw.shape
    if K != K2 or sw.shape != (1, N):
        raise ValueError(f"shape mismatch: x {x.shape}, qw {qw.shape}, "
                         f"sw {sw.shape}")
    if activation not in (None, "gelu"):
        raise ValueError(f"unsupported activation {activation!r}")
    if want_preact and activation is None:
        raise ValueError("want_preact without an activation is just the "
                         "plain output — drop the flag")
    bm, bn, bk = _pick(M, block_m), _pick(N, block_n), _pick(K, block_k)
    in_specs = [pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
                pl.BlockSpec((1, bn), lambda i, j, k: (0, j))]
    operands = [x, qw, sw]
    if bias is not None:
        bias = bias.reshape(1, -1).astype(jnp.float32)
        if bias.shape != (1, N):
            raise ValueError(f"bias shape {bias.shape} != (1, {N})")
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j, k: (0, j)))
        operands.append(bias)
    if residual is not None:
        if residual.shape != (M, N):
            raise ValueError(f"residual shape {residual.shape} != "
                             f"({M}, {N})")
        in_specs.append(pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)))
        operands.append(residual)
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]
    out_shape = [jax.ShapeDtypeStruct((M, N), x.dtype)]
    if want_preact:
        out_specs.append(pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)))
        out_shape.append(jax.ShapeDtypeStruct((M, N), x.dtype))
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, activation=activation,
                          has_bias=bias is not None,
                          want_preact=want_preact,
                          has_residual=residual is not None),
        grid=(M // bm, N // bn, K // bk),
        in_specs=in_specs,
        out_specs=out_specs if want_preact else out_specs[0],
        out_shape=out_shape if want_preact else out_shape[0],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(*operands)
    return out


@functools.partial(jax.jit, static_argnames=("want_g", "block_m",
                                             "block_n", "block_k",
                                             "interpret"))
def quantized_matmul_dgelu(da: jax.Array, pre: jax.Array, qwt: jax.Array,
                           swt: jax.Array, *, want_g: bool = False,
                           block_m: int = 512, block_n: int = 2048,
                           block_k: int = 512, interpret: bool = False):
    """``(da * gelu'(pre)) [M, K] @ (qwt [K, N] int8 * swt [1, N])``.

    The gelu backward runs in the matmul PROLOGUE (VMEM) — ``g = da *
    gelu'(pre)`` never round-trips HBM for the dgrad.  ``want_g`` also
    emits ``g`` (in ``da.dtype``) for the wgrad/bias-grad path, written
    on the last output-column pass; the return is then ``(dx, g)``.
    ``pre`` is the ``want_preact`` output of :func:`quantized_matmul`
    (same storage rounding).  This is the re-quantized-weight (TN)
    formulation; the MLP's default backward is the cheaper
    :func:`quantized_matmul_nt`.
    """
    M, K = da.shape
    if pre.shape != (M, K):
        raise ValueError(f"pre shape {pre.shape} != da shape {da.shape}")
    K2, N = qwt.shape
    if K != K2 or swt.shape != (1, N):
        raise ValueError(f"shape mismatch: da {da.shape}, qwt {qwt.shape}, "
                         f"swt {swt.shape}")
    bm, bn, bk = _pick(M, block_m), _pick(N, block_n), _pick(K, block_k)
    in_specs = [pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
                pl.BlockSpec((1, bn), lambda i, j, k: (0, j))]
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]
    out_shape = [jax.ShapeDtypeStruct((M, N), da.dtype)]
    if want_g:
        out_specs.append(pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)))
        out_shape.append(jax.ShapeDtypeStruct((M, K), da.dtype))
    out = pl.pallas_call(
        functools.partial(_qmm_dgelu_kernel, want_g=want_g),
        grid=(M // bm, N // bn, K // bk),
        in_specs=in_specs,
        out_specs=out_specs if want_g else out_specs[0],
        out_shape=out_shape if want_g else out_shape[0],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(da, pre, qwt, swt)
    return out
