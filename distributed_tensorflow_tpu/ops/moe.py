"""Mixture-of-Experts — expert parallelism over the ``expert`` mesh axis (EP).

The reference is a dense 2-layer MLP with no conditional computation anywhere
(``distributed.py:67-81``); MoE is part of this framework's beyond-parity
distributed surface, designed TPU-first:

- **Dense dispatch/combine** (the GShard/Switch pattern): routing is expressed
  as one-hot einsums over a static per-expert *capacity*, so the whole layer is
  fixed-shape MXU work — no dynamic shapes, no host control flow, one compiled
  program.  When expert weights are sharded over the ``expert`` mesh axis,
  GSPMD lowers the dispatch/combine einsums to all-to-alls over ICI.
- **Stacked expert weights**: the per-expert FFN is an ``nn.vmap``-lifted dense
  pair whose parameters carry a leading ``[num_experts, ...]`` dim — sharded by
  :func:`moe_sharding_rules` (``P("expert", ...)``), exactly like pipeline
  stages shard over ``pipe``.
- **Grouped routing with static capacity** (the GShard token-group trick):
  tokens route within fixed-size groups (default: one group per sequence), so
  capacity is ``C = ceil(capacity_factor * k * S / E)`` per group and the
  dispatch/combine tensors are ``[G, S, E, C]`` — linear in the batch, never
  the O(T^2) a single global group would give with few experts.  Tokens that
  overflow an expert's capacity are dropped (their combine weight is zero),
  keeping shapes static; the router is fp32 end-to-end so tie-breaks and the
  softmax normalizer never run in bfloat16.
- **Load-balancing aux loss** (Switch Transformer form): sown into the
  ``moe_losses`` collection; training code applies it via
  :func:`collect_aux_loss` so the module's return type stays a plain array.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

AUX_LOSS_COLLECTION = "moe_losses"
# Default load-balance loss coefficient (SGD-tuned); single home for the
# registry loss, the driver dry-run, and tests.
DEFAULT_AUX_WEIGHT = 0.01


class _ExpertFFN(nn.Module):
    """One expert's dense→gelu→dense block (vmapped over experts)."""

    intermediate_size: int
    hidden_size: int
    dtype: Any

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:  # [C, H] -> [C, H]
        h = nn.Dense(self.intermediate_size, dtype=self.dtype, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(self.hidden_size, dtype=self.dtype, name="wo")(h)


class MoeMlp(nn.Module):
    """Top-k gated mixture-of-experts FFN, drop-in for a dense MLP block.

    Input/output: ``[..., hidden]`` (leading dims are flattened into a token
    axis for routing).  Sows the load-balancing loss into ``moe_losses``.
    """

    num_experts: int
    intermediate_size: int
    top_k: int = 2
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32
    # Tokens per routing group.  None: for [B, S, H] inputs each sequence is a
    # group (capacity and dispatch memory stay linear in batch); for [T, H]
    # inputs everything is one group.
    group_size: int | None = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = jnp.dtype(self.dtype)
        orig_shape = x.shape
        hidden = x.shape[-1]
        tokens = x.reshape(-1, hidden)
        T = tokens.shape[0]
        S = self.group_size or (x.shape[-2] if x.ndim >= 3 else T)
        if T % S:
            raise ValueError(f"{T} tokens not divisible by group size {S}")
        G = T // S
        groups = tokens.reshape(G, S, hidden)
        E = self.num_experts
        k = min(self.top_k, E)
        C = max(1, math.ceil(self.capacity_factor * k * S / E))

        # Router in fp32: gate probabilities drive both the combine weights and
        # the aux loss; an 8-bit mantissa would make tie-breaks nondeterministic.
        gate_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                               param_dtype=jnp.float32, name="router")(
                                   groups.astype(jnp.float32))
        probs = jax.nn.softmax(gate_logits, axis=-1)            # [G, S, E]

        # Iterative top-k with per-group capacity: slot i fills experts after
        # slots < i (GShard ordering).  All shapes static; the loop unrolls at
        # trace time.
        fills = jnp.zeros((G, E), jnp.float32)  # tokens already placed / expert
        remaining = probs
        selections = []                          # (gate, kept_mask, position)
        for _ in range(k):
            idx = jnp.argmax(remaining, axis=-1)
            onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)   # [G, S, E]
            pos = jnp.cumsum(onehot, axis=1) - onehot + fills[:, None, :]
            pos_t = jnp.sum(pos * onehot, axis=-1)               # [G, S]
            kept = onehot * (pos_t < C).astype(jnp.float32)[..., None]
            gate = jnp.sum(remaining * onehot, axis=-1)          # [G, S]
            selections.append((gate, kept, pos_t))
            fills = fills + kept.sum(axis=1)
            remaining = remaining * (1.0 - onehot)

        # Switch-style balance loss from the top-1 assignment (pre-capacity),
        # over all tokens: E * sum_e( fraction_routed_to_e * mean_prob_e );
        # equals 1.0 at perfect balance, grows toward E as routing collapses.
        top1 = jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=jnp.float32)
        aux = E * jnp.sum(jnp.mean(top1, axis=(0, 1))
                          * jnp.mean(probs, axis=(0, 1)))
        self.sow(AUX_LOSS_COLLECTION, "aux_loss", aux)

        # Normalize gates over the selected k (dropped slots keep their share
        # of the denominator — a dropped token loses that fraction of output,
        # the GShard behavior).
        denom = jnp.maximum(sum(g for g, _, _ in selections), 1e-9)
        combine = jnp.zeros((G, S, E, C), jnp.float32)
        for gate, kept, pos_t in selections:
            slot = jax.nn.one_hot(pos_t.astype(jnp.int32), C,
                                  dtype=jnp.float32)             # [G, S, C]
            combine = combine + ((gate / denom)[..., None, None]
                                 * kept[..., None] * slot[..., None, :])
        dispatch = (combine > 0.0).astype(dtype)

        # Dispatch → per-expert compute → combine.  With expert weights sharded
        # over ``expert`` these three contractions become
        # all-to-all / local-MXU / all-to-all under GSPMD.
        expert_in = jnp.einsum("gsec,gsh->egch", dispatch, groups.astype(dtype))
        experts = nn.vmap(
            _ExpertFFN,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(self.intermediate_size, hidden, self.dtype, name="experts")
        expert_out = experts(expert_in.reshape(E, G * C, hidden))
        expert_out = expert_out.reshape(E, G, C, hidden)
        out = jnp.einsum("gsec,egch->gsh", combine,
                         expert_out.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        return out.astype(x.dtype).reshape(orig_shape)


def moe_sharding_rules(prefix: str = "") -> list[tuple[str, P]]:
    """(regex, spec) rules placing stacked expert weights over ``expert``.

    Returned as a plain list so callers can splice them into a model's wider
    rule set (e.g. BERT's tensor-parallel rules) before building
    :class:`..parallel.sharding.ShardingRules`.
    """
    return [
        (prefix + r"experts/(wi|wo)/kernel", P("expert", None, None)),
        (prefix + r"experts/(wi|wo)/bias", P("expert", None)),
    ]


def collect_aux_loss(mutated_collections: dict) -> jax.Array:
    """Mean load-balancing loss over every MoE layer that sowed one.

    ``mutated_collections`` is the second return of
    ``module.apply(..., mutable=[AUX_LOSS_COLLECTION])``.
    """
    leaves = jax.tree.leaves(mutated_collections.get(AUX_LOSS_COLLECTION, {}))
    if not leaves:
        return jnp.float32(0.0)
    return sum(leaves) / len(leaves)
