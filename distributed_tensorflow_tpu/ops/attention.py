"""Attention ops — XLA-lowered by default, pluggable pallas/ring backends.

The reference has no attention anywhere (inputs are flat 784-dim vectors,
``distributed.py:75``); this op exists for the BASELINE.json BERT-tiny config
and the framework's first-class long-context support.  Design: a single
functional entry point that jit-compiles to fused MXU matmuls on TPU; callers
pick a backend explicitly (``"xla"`` default, ``"pallas"`` fused-flash on real
TPU, ``"ring"`` for sequence-parallel meshes via
:mod:`..parallel.ring`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dot_product_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H, D]
    v: jax.Array,  # [B, S, H, D]
    mask: jax.Array | None = None,  # broadcastable to [B, H, S, S]; 1 = attend
    backend: str = "xla",
) -> jax.Array:
    """Multi-head scaled dot-product attention, batch-major BSHD layout."""
    if backend == "pallas":
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, mask=mask)
    if backend != "xla":
        raise ValueError(f"Unknown attention backend: {backend!r}")
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    # [B, H, S, S] logits — einsum keeps it one fused MXU contraction.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
