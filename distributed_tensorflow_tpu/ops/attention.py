"""Attention ops — XLA-lowered by default, pallas-flash and ring backends.

The reference has no attention anywhere (inputs are flat 784-dim vectors,
``distributed.py:75``); this op exists for the BASELINE.json BERT-tiny config
and the framework's first-class long-context support.  One functional entry
point, three backends:

- ``"xla"`` (default): one fused pair of MXU einsums; logits and softmax in
  fp32 regardless of activation dtype (bfloat16 in = bfloat16 out, but the
  normalizer never accumulates in 8-bit-mantissa precision).
- ``"pallas"``: blockwise flash attention kernel
  (:mod:`.pallas.flash_attention`) — O(S) memory, VMEM-resident scores.
- ``"ring"``: sequence-parallel exact attention over the ``seq`` mesh axis
  (:mod:`..parallel.ring`); requires ``mesh``.
- ``"ulysses"``: sequence-parallel exact attention via head/sequence
  all-to-all (:mod:`..parallel.ulysses`); requires ``mesh`` and heads
  divisible by the ``seq`` axis size.

Masks: ``kv_mask`` is the key-padding form [B, S] (nonzero = attend) accepted
by every backend; the fully-general ``mask`` (broadcastable to [B, H, S, S])
is XLA-only.  ``causal`` composes with either.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

# Mesh used by the ring backend when callers can't thread one through (flax
# modules configure attention by string).  Set at trace time via
# attention_mesh(); read when dot_product_attention builds the shard_map.
_DEFAULT_MESH = None


@contextlib.contextmanager
def attention_mesh(mesh):
    """Make ``mesh`` the default for mesh-requiring backends (e.g. ``ring``).

    Wrap the *first* (tracing) call of a jitted function whose model uses
    ``attention_backend="ring"``; the mesh is captured into the compiled
    program, so steady-state calls don't need the context.
    """
    global _DEFAULT_MESH
    prev = _DEFAULT_MESH
    _DEFAULT_MESH = mesh
    try:
        yield
    finally:
        _DEFAULT_MESH = prev


def dot_product_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H, D]
    v: jax.Array,  # [B, S, H, D]
    mask: jax.Array | None = None,      # broadcastable to [B, H, S, S]; 1 = attend
    kv_mask: jax.Array | None = None,   # [B, S]; nonzero = attend (all backends)
    *,
    causal: bool = False,
    window: int = 0,
    backend: str = "xla",
    mesh=None,
) -> jax.Array:
    """Multi-head scaled dot-product attention, batch-major BSHD layout.

    ``window`` > 0 (requires ``causal``) is sliding-window attention: each
    query sees its ``window`` most recent keys only.  Supported by every
    backend: xla masks, pallas skips whole blocks outside the band
    (O(S*window) compiled cost), ulysses threads it through its gathered
    local attention, and ring truncates to the hops whose chunks intersect
    the band (fewer collectives, not just fewer FLOPs).
    """
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if backend == "pallas":
        if mask is not None:
            raise ValueError("pallas backend supports kv_mask/causal, not a "
                             "full [B,H,S,S] mask")
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, kv_mask=kv_mask, causal=causal,
                               window=window)
    if backend in ("ring", "ulysses"):
        if mask is not None:
            raise ValueError(f"{backend} backend supports kv_mask/causal, "
                             "not a full [B,H,S,S] mask")
        if mesh is None:
            mesh = _DEFAULT_MESH
        if mesh is None:
            raise ValueError(f"{backend} backend needs mesh= (with a 'seq' "
                             "axis), passed directly or via attention_mesh(...)")
        from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
        n_data = mesh.shape.get(DATA_AXIS, 1)
        n_seq = mesh.shape.get(SEQ_AXIS, 1)
        # Compose with tensor parallelism automatically: when heads divide
        # the model axis, each model shard runs its own independent
        # sequence-parallel attention over its heads.
        n_model = mesh.shape.get(MODEL_AXIS, 1)
        heads_sharded = n_model > 1 and q.shape[2] % n_model == 0
        local_heads = q.shape[2] // (n_model if heads_sharded else 1)
        if q.shape[0] % n_data or q.shape[1] % n_seq or (
                backend == "ulysses" and local_heads % n_seq):
            # Shapes that don't tile the mesh (model.init dummies, ragged eval
            # tails, head counts the all-to-all can't split) take the XLA
            # path — both backends are exact attention, so this changes
            # layout, never math.  Static shapes: fixed per compiled program.
            backend = "xla"
        elif backend == "ring":
            from ..parallel.ring import make_ring_attention
            return make_ring_attention(mesh, causal=causal, window=window,
                                       heads_sharded=heads_sharded)(
                                           q, k, v, kv_mask)
        else:
            from ..parallel.ulysses import make_ulysses_attention
            return make_ulysses_attention(mesh, causal=causal, window=window,
                                          heads_sharded=heads_sharded)(
                                              q, k, v, kv_mask)
    if backend != "xla":
        raise ValueError(f"Unknown attention backend: {backend!r}")

    S = q.shape[1]
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(depth))
    # fp32 logits + softmax (bert.py's documented invariant); einsum stays one
    # fused MXU contraction with fp32 accumulation.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.ones((1, 1, 1, 1), jnp.bool_)
    if mask is not None:
        valid = valid & mask.astype(bool)
    if kv_mask is not None:
        valid = valid & (kv_mask[:, None, None, :] != 0)
    if causal:
        band = jnp.tril(jnp.ones((S, S), jnp.bool_))
        if window:
            band = band & ~jnp.tril(jnp.ones((S, S), jnp.bool_), -window)
        valid = valid & band[None, None]
    valid = jnp.broadcast_to(valid, logits.shape)
    logits = jnp.where(valid, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    # Fully-masked rows: softmax of all-min logits is uniform; define as 0.
    weights = weights * jnp.any(valid, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)
