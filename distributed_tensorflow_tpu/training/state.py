"""Training state — parameters + optimizer slots + global_step, resident in TPU HBM.

Replaces the reference's PS-resident ``tf.Variable`` set (N2): ``global_step``
(``distributed.py:65``) and model/optimizer variables live in one pytree whose
placement is governed by :mod:`..parallel.sharding` rules instead of
``replica_device_setter``.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    """Pure-pytree train state (jit/pjit friendly; checkpointable as-is)."""

    params: Any
    opt_state: Any
    global_step: jax.Array  # scalar int32; reference inits it to 1 (distributed.py:65)

    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    # Non-trainable model collections (e.g. BatchNorm batch_stats); None for
    # stateless models.
    model_state: Any = None

    # Training-time PRNG state (dropout etc.); None for deterministic models.
    # Split per step by rng-aware train steps; not checkpointed (a resumed
    # run re-seeds — dropout noise need not replay).
    rng: Any = None

    # Exponential moving average of params (None = disabled).  Updated by
    # ema-aware train steps after each optimizer step; evaluation and the
    # final test use the EMA weights when present.  Checkpointed.
    ema_params: Any = None

    @classmethod
    def create(cls, apply_fn: Callable, params: Any,
               tx: optax.GradientTransformation,
               model_state: Any = None, rng: Any = None,
               ema_params: Any = None) -> "TrainState":
        return cls(
            params=params,
            opt_state=tx.init(params),
            # Reference parity: global_step starts at 1 (distributed.py:65).
            global_step=jnp.asarray(1, jnp.int32),
            apply_fn=apply_fn,
            tx=tx,
            model_state=model_state,
            rng=rng,
            ema_params=ema_params,
        )

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(params=new_params, opt_state=new_opt_state,
                            global_step=self.global_step + 1)


def gradient_descent(learning_rate: float) -> optax.GradientTransformation:
    """The reference optimizer: plain SGD (``distributed.py:89``)."""
    return optax.sgd(learning_rate)
