"""Optimizer zoo + learning-rate schedules (optax-based).

The reference exposes exactly one optimizer — plain SGD at a fixed rate
(``GradientDescentOptimizer``, reference ``distributed.py:89``).  A usable
framework needs the standard families and schedules on top; everything here
is an ``optax.GradientTransformation`` so it drops into
:class:`..training.state.TrainState` unchanged and its slot variables ride
the same HBM sharding/checkpoint path as the parameters.

Composition order (outermost first): global-norm gradient clip → weight decay
→ base optimizer with the requested schedule.  adamw/lamb apply true
*decoupled* decay inside their update rule; for the other optimizers a
nonzero ``weight_decay`` is classic L2 regularization (the decay term joins
the gradient *before* any moment normalization).  Schedules count steps in
the optimizer state, so checkpoint/restore resumes the schedule exactly.
"""

from __future__ import annotations

from typing import Callable

import optax

OPTIMIZERS = ("sgd", "momentum", "nesterov", "adam", "adamw", "lamb",
              "adagrad", "rmsprop", "adafactor")
SCHEDULES = ("constant", "cosine", "linear", "rsqrt")

# Optimizers whose update rule already includes decoupled weight decay; for
# the rest, nonzero weight_decay is chained in as add_decayed_weights, i.e.
# L2 regularization (coupled — see module docstring).
_BUILTIN_DECAY = ("adamw", "lamb", "adafactor")


def make_schedule(name: str, learning_rate: float, *,
                  warmup_steps: int = 0, decay_steps: int = 0,
                  end_lr_factor: float = 0.0) -> Callable | float:
    """Build a learning-rate schedule.

    ``decay_steps`` is the total schedule horizon (typically
    ``--train_steps``); the decaying portion spans
    ``decay_steps - warmup_steps``.  ``end_lr_factor`` sets the final rate as
    a fraction of the peak.  ``constant`` ignores everything but warmup
    (linear ramp to the fixed rate, if requested).
    """
    if name not in SCHEDULES:
        raise ValueError(f"Unknown lr schedule {name!r}; one of {SCHEDULES}")
    if warmup_steps < 0:
        raise ValueError(f"warmup_steps must be >= 0, got {warmup_steps}")
    if name != "constant":
        # constant ignores the horizon entirely (long warmup on a short run
        # is legitimate); the decaying schedules need a real span.
        if decay_steps <= 0:
            raise ValueError(f"lr schedule {name!r} needs decay_steps > 0 "
                             f"(got {decay_steps}); pass the training horizon")
        if warmup_steps >= decay_steps:
            raise ValueError(f"warmup_steps={warmup_steps} must be in "
                             f"[0, decay_steps={decay_steps})")
    end_value = learning_rate * end_lr_factor

    if name == "constant":
        if warmup_steps:
            return optax.linear_schedule(0.0, learning_rate, warmup_steps)
        return learning_rate
    if name == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps else learning_rate,
            peak_value=learning_rate, warmup_steps=warmup_steps,
            decay_steps=decay_steps, end_value=end_value)
    if name == "linear":
        ramp = optax.linear_schedule(0.0, learning_rate, max(warmup_steps, 1))
        decay = optax.linear_schedule(learning_rate, end_value,
                                      decay_steps - warmup_steps)
        if warmup_steps:
            return optax.join_schedules([ramp, decay], [warmup_steps])
        return decay

    # rsqrt: linear warmup, then lr * sqrt(warmup / global_step) — the
    # transformer-standard inverse-square-root decay.  join_schedules hands
    # the post-boundary schedule a *shifted* step, so add the offset back.
    base = max(warmup_steps, 1)

    def rsqrt(step_after_warmup):
        import jax.numpy as jnp
        global_step = jnp.maximum(step_after_warmup + base, base)
        return learning_rate * jnp.sqrt(base / global_step)

    if warmup_steps:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, learning_rate, warmup_steps), rsqrt],
            [warmup_steps])
    return rsqrt


def make_optimizer(name: str, learning_rate, *, momentum: float = 0.9,
                   weight_decay: float = 0.0,
                   grad_clip_norm: float = 0.0) -> optax.GradientTransformation:
    """Build an optimizer by name; ``learning_rate`` may be a float or a
    schedule from :func:`make_schedule`."""
    if name not in OPTIMIZERS:
        raise ValueError(f"Unknown optimizer {name!r}; one of {OPTIMIZERS}")

    if name == "sgd":
        base = optax.sgd(learning_rate)
    elif name == "momentum":
        base = optax.sgd(learning_rate, momentum=momentum)
    elif name == "nesterov":
        base = optax.sgd(learning_rate, momentum=momentum, nesterov=True)
    elif name == "adam":
        base = optax.adam(learning_rate)
    elif name == "adamw":
        base = optax.adamw(learning_rate, weight_decay=weight_decay)
    elif name == "lamb":
        base = optax.lamb(learning_rate, weight_decay=weight_decay)
    elif name == "adagrad":
        base = optax.adagrad(learning_rate)
    elif name == "adafactor":
        # The TPU-era memory-efficient optimizer: factored second moments
        # (row+col vectors instead of a full slot per matrix), sublinear
        # optimizer memory — the slot-variable counterpart of --fsdp's
        # sharding lever.  min_dim_size_to_factor=128 keeps small tensors
        # on exact second moments.
        base = optax.adafactor(learning_rate,
                               min_dim_size_to_factor=128,
                               weight_decay_rate=weight_decay or None)
    else:
        base = optax.rmsprop(learning_rate, momentum=momentum)

    chain = []
    if grad_clip_norm > 0.0:
        chain.append(optax.clip_by_global_norm(grad_clip_norm))
    if weight_decay > 0.0 and name not in _BUILTIN_DECAY:
        chain.append(optax.add_decayed_weights(weight_decay))
    chain.append(base)
    return optax.chain(*chain) if len(chain) > 1 else base


def freeze_except(tx: optax.GradientTransformation, params,
                  pattern: str) -> tuple[optax.GradientTransformation, int, int]:
    """Selective fine-tuning: only parameters whose path matches ``pattern``
    train; the rest are frozen (``optax.set_to_zero`` — no update, and no
    optimizer slots for them, so frozen layers also cost no slot memory).

    The reference could only ever train everything (``opt.minimize``,
    reference ``distributed.py:102``); head-only / layer-frozen fine-tuning
    is the standard transfer recipe this enables.  Returns
    ``(wrapped_tx, n_trainable, n_total)`` — callers re-init the optimizer
    state from the wrapped transformation.
    """
    import re

    import jax

    from ..parallel.sharding import path_str

    pat = re.compile(pattern)

    def labels(tree):
        return jax.tree_util.tree_map_with_path(
            lambda p, _: "train" if pat.search(path_str(p)) else "freeze",
            tree)

    lab = labels(params)
    leaves = jax.tree.leaves(params)
    flags_ = jax.tree.leaves(lab)
    n_total = sum(int(l.size) for l in leaves)
    n_train = sum(int(l.size) for l, f in zip(leaves, flags_) if f == "train")
    if n_train == 0:
        raise ValueError(
            f"--trainable_params pattern {pattern!r} matches no parameters; "
            "nothing would train")
    wrapped = optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels)
    return wrapped, n_train, n_total


def _flag_schedule(FLAGS):
    """The schedule the ``--optimizer`` override uses — ONE resolution of
    the flag surface, shared by the optimizer builder and the logger so the
    logged rate can never diverge from the applied one."""
    decay_steps = getattr(FLAGS, "decay_steps", 0) or FLAGS.train_steps
    return make_schedule(getattr(FLAGS, "lr_schedule", "constant"),
                         FLAGS.learning_rate,
                         warmup_steps=getattr(FLAGS, "warmup_steps", 0),
                         decay_steps=decay_steps,
                         end_lr_factor=getattr(FLAGS, "end_lr_factor", 0.0))


def schedule_from_flags(FLAGS):
    """The ``--optimizer`` override's learning-rate schedule as a callable
    ``step_count -> rate`` — or None when no override is active (each model's
    own optimizer then sets its internal rate).  The loop logs this alongside
    loss/accuracy so schedule behavior is observable."""
    if not (getattr(FLAGS, "optimizer", "") or ""):
        return None
    schedule = _flag_schedule(FLAGS)
    if callable(schedule):
        return schedule
    return lambda step, value=schedule: value


def from_flags(FLAGS, *, default=None):
    """Optimizer from the CLI surface; ``None`` when the user didn't override.

    ``--optimizer=''`` (the default) keeps each model's own optimizer (SGD for
    the reference workloads, Adam for transformers).  Any explicit name takes
    full control: schedule horizon defaults to ``--train_steps``.
    """
    name = getattr(FLAGS, "optimizer", "") or ""
    if not name:
        # The tuning knobs below only act through an explicit optimizer
        # override; flag it rather than silently dropping them.
        ignored = [flag for flag, active in (
            ("grad_clip_norm", getattr(FLAGS, "grad_clip_norm", 0.0) > 0),
            ("weight_decay", getattr(FLAGS, "weight_decay", 0.0) > 0),
            ("warmup_steps", getattr(FLAGS, "warmup_steps", 0) > 0),
            ("lr_schedule",
             getattr(FLAGS, "lr_schedule", "constant") != "constant"),
        ) if active]
        if ignored:
            print("WARNING: " + ", ".join(f"--{f}" for f in ignored)
                  + " ignored without --optimizer (the model's own optimizer "
                  "is in effect); set --optimizer to apply them")
        return default
    lr = _flag_schedule(FLAGS)
    return make_optimizer(name, lr,
                          momentum=getattr(FLAGS, "momentum", 0.9),
                          weight_decay=getattr(FLAGS, "weight_decay", 0.0),
                          grad_clip_norm=getattr(FLAGS, "grad_clip_norm", 0.0))
