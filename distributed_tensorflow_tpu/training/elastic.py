"""Elastic membership — shrink/grow the replica set on failure instead of
stalling (docs/fault_tolerance.md, "Elastic membership").

The coordination service owns a monotonically increasing *membership
epoch* over the active task set (``csrc/coordination/coord.cc``): lease
expiry or an explicit ``LEAVE`` shrinks the set and bumps the epoch, a
re-``REGISTER`` grows it and bumps again, and barriers release on the
active set rather than ``num_tasks``.  A
:class:`..cluster.coordination.MembershipWatcher` mirrors ``(epoch,
active_task_ids)`` into each worker; this module is what the training
side *does* with an epoch change, in one of two modes:

- **in-place degradation** (``mode="in_place"``, single-controller masked
  sync): an epoch change just flips the per-replica mask fed to
  ``build_masked_sync_train_step`` — survivors keep stepping at R<N with
  renormalized gradients, no stall.  A worker that finds *itself* outside
  the active set (its lease expired, it was explicitly evicted, or chaos
  made it LEAVE) pauses, re-registers when reachable again, restores from
  the chief's latest published checkpoint (its own weights went stale
  while it was masked out), and resumes — the grow half of the cycle.
- **checkpoint–reshard–resume** (``mode="reshard"``, multi-controller,
  where XLA's device topology is fixed at startup): the chief reacts to a
  shrink by publishing a *stop step* a margin ahead through the KV store;
  every process (lockstep in SPMD, so all at the same global step) takes
  the collective durable save at that step, the chief publishes the new
  cluster spec under ``dtf/elastic/cluster_spec``, and the processes exit
  with ``result.resharded`` set so the launcher can restart them into the
  smaller mesh through the existing cross-topology restore.  The margin
  must exceed ``watcher_interval x step_rate`` so every process learns of
  the stop step before reaching it (documented in fault_tolerance.md).

Every resize emits ``kind="recovery"`` telemetry (``elastic_shrink`` /
``elastic_grow`` from the watcher; ``elastic_leave`` / ``elastic_rejoin``
/ ``elastic_reshard`` from this controller) that ``tools/summarize_run``
rolls into the run report.
"""

from __future__ import annotations

import json
import time
from typing import Any

from ..cluster.coordination import CoordinationError, MembershipWatcher
from ..utils import faults

RESHARD_KEY = "dtf/elastic/reshard"
CLUSTER_SPEC_KEY = "dtf/elastic/cluster_spec"


class ElasticController:
    """Consumes membership epochs inside the training loop.

    ``on_step(state, step)`` is called once per completed step (after
    ``faults.on_step``, so a ``DTF_CHAOS`` ``evict_at_step`` directive is
    already armed when we look) and returns ``(state, stop)``: ``state``
    may be a freshly restored one after a rejoin, ``stop`` requests a
    loop exit (reshard mode only).
    """

    def __init__(self, *, watcher: MembershipWatcher, client,
                 task_index: int, num_workers: int,
                 supervisor=None, mode: str = "in_place",
                 is_chief: bool = False, telemetry=None,
                 print_fn=print, rejoin_timeout: float = 120.0,
                 poll_interval: float = 0.25,
                 reshard_margin_steps: int = 20):
        if mode not in ("in_place", "reshard"):
            raise ValueError(f"mode must be in_place or reshard, got {mode!r}")
        self._watcher = watcher
        self._client = client
        self._task = task_index
        self._num_workers = num_workers
        self._supervisor = supervisor
        self.mode = mode
        self._is_chief = is_chief
        self._telemetry = telemetry
        self._print = print_fn
        self._rejoin_timeout = rejoin_timeout
        self._poll = poll_interval
        self._margin = int(reshard_margin_steps)
        #: transition counters (test surface)
        self.transitions = {"left": 0, "rejoined": 0, "resharded": 0}
        self._reshard_request: dict | None = None

    def attach_telemetry(self, telemetry) -> None:
        self._telemetry = telemetry
        self._watcher.attach_telemetry(telemetry)

    def _emit(self, action: str, step: int, **fields) -> None:
        if self._telemetry is not None:
            self._telemetry.emit("recovery", step=max(int(step), 0),
                                 action=action, task=self._task, **fields)

    # ------------------------------------------------------------- hooks

    def on_step(self, state: Any, step: int) -> tuple[Any, bool]:
        # Surface a latched background-thread crash (dead heartbeat/health
        # thread) on the step loop: the masked hot path otherwise makes no
        # protocol calls, and a worker whose beats silently stopped would
        # train as a zombie until eviction — fail loudly instead.
        self._client.check_background()
        if self.mode == "reshard":
            return self._reshard_step(state, step)
        return self._in_place_step(state, step)

    # -- in-place degradation --------------------------------------------

    def _in_place_step(self, state: Any, step: int) -> tuple[Any, bool]:
        injector = faults.active()
        if injector is not None and injector.take_leave_request():
            # Chaos-driven deterministic eviction: LEAVE before the
            # partition window opens (an immediate epoch shrink — the
            # survivors resize without waiting out our lease).
            try:
                self._client.leave()
            except CoordinationError:
                pass
            injector.begin_partition()
            self.transitions["left"] += 1
            self._print(f"Worker {self._task}: left the replica set at "
                        f"global step {step} (injected eviction)")
            self._emit("elastic_leave", step)
            return self._await_rejoin(state, step), False
        epoch, active = self._watcher.snapshot()
        if epoch > 0 and self._task not in active:
            # The server evicted us (lease expiry while we stalled, or an
            # explicit RECONFIGURE): stop stepping — our gradients are
            # masked out anyway — and walk the rejoin path.
            self._print(f"Worker {self._task}: evicted from the replica "
                        f"set (epoch {epoch}) at global step {step}")
            self._emit("elastic_evicted", step, epoch=epoch)
            return self._await_rejoin(state, step), False
        return state, False

    def _await_rejoin(self, state: Any, step: int) -> Any:
        """Block until re-admitted: wait out any injected partition,
        re-register (the grow half of the epoch cycle), then restore the
        cluster's latest published checkpoint — the weights this worker
        holds predate the steps the survivors took without it."""
        deadline = time.monotonic() + self._rejoin_timeout
        while True:
            injector = faults.active()
            if injector is not None and injector.partitioned():
                time.sleep(self._poll)
                continue
            try:
                self._client.register(timeout=5.0,
                                      poll_interval=self._poll)
                break
            except CoordinationError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(self._poll)
        if self._supervisor is not None:
            state = self._supervisor.restore_for_rejoin()
        epoch, active = self._watcher.poll()
        restored = int(getattr(state, "global_step", 0))
        self.transitions["rejoined"] += 1
        self._print(f"Worker {self._task}: rejoined the replica set at "
                    f"epoch {epoch} (active {list(active)}); restored "
                    f"global step {restored}")
        self._emit("elastic_rejoin", restored, epoch=epoch,
                   active_count=len(active))
        return state

    # -- checkpoint-reshard-resume ---------------------------------------

    def _reshard_step(self, state: Any, step: int) -> tuple[Any, bool]:
        epoch, active = self._watcher.snapshot()
        shrunk = epoch > 0 and len(active) < self._num_workers
        if not shrunk and self._reshard_request is None:
            return state, False
        if self._reshard_request is None:
            self._reshard_request = self._negotiate_stop_step(step, epoch,
                                                              active)
            if self._reshard_request is None:
                return state, False
        request = self._reshard_request
        if step < int(request["stop_step"]):
            return state, False
        # Stop step reached — lockstep SPMD puts every process here at the
        # same global step, so the collective save below is consistent.
        if self._supervisor is not None:
            self._supervisor.maybe_save(state, force=True)
            self._supervisor.wait_until_finished()
        if self._is_chief:
            spec = {"epoch": request["epoch"],
                    "active": request["active"],
                    "num_workers": len(request["active"]),
                    "checkpoint_step": int(getattr(state, "global_step",
                                                   step))}
            try:
                self._client.kv_set(CLUSTER_SPEC_KEY, json.dumps(spec))
            except CoordinationError:
                self._print(f"Worker {self._task}: could not publish the "
                            "elastic cluster spec (coordinator "
                            "unreachable); relaunch from MEMBERS instead")
        self.transitions["resharded"] += 1
        self._print(f"Worker {self._task}: elastic reshard at global step "
                    f"{step} (epoch {request['epoch']}, active "
                    f"{request['active']}): checkpoint durable; exiting "
                    f"for relaunch into the smaller mesh")
        self._emit("elastic_reshard", step, epoch=request["epoch"],
                   active_count=len(request["active"]))
        return state, True

    def _negotiate_stop_step(self, step: int, epoch: int,
                             active: tuple[int, ...]) -> dict | None:
        """Chief publishes ``stop_step = now + margin``; everyone else
        polls for it (all processes observed the shrink through their own
        watchers, so the poll starts well before the stop step)."""
        if self._is_chief:
            request = {"epoch": epoch, "stop_step": int(step) + self._margin,
                       "active": list(active)}
            try:
                self._client.kv_set(RESHARD_KEY, json.dumps(request))
            except CoordinationError:
                return None  # retry next step
            self._print(f"Worker {self._task}: membership shrank to "
                        f"{list(active)} (epoch {epoch}); resharding at "
                        f"global step {request['stop_step']}")
            self._emit("elastic_reshard_requested", step, epoch=epoch,
                       stop_step=request["stop_step"])
            return request
        try:
            value = self._client.kv_get(RESHARD_KEY)
        except CoordinationError:
            return None
        if value is None:
            return None
        try:
            request = json.loads(value)
        except ValueError:
            return None
        if int(request.get("epoch", -1)) < epoch:
            return None  # stale request from an earlier resize
        return request
