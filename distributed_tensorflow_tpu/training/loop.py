"""Training loop, eval, metrics (C11/C12) — the reference's observable behavior.

Matches the reference step loop (``distributed.py:133-165``): shuffled
``next_batch`` feed, validation on the full split every 10000 local steps,
per-step ``Worker N: ... step ... loss ... accuracy`` line, stop when the
shared ``global_step`` reaches ``train_steps``, wall-clock elapsed time, and a
final full-test-split accuracy print.

TPU-native deltas:
- the per-step *extra* forward pass the reference runs for train accuracy
  (``:148-149``) is fused into the train step's aux metrics — same printed
  quantity, one forward instead of two;
- host→device feed is overlapped with compute via the async dispatch queue
  (device_put of the next batch happens while the previous step runs).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..data.datasets import DataSet
from ..data.prefetch import DevicePrefetcher
from ..parallel import mesh as mesh_lib
from ..parallel.sharding import path_str
from ..utils import faults, tracing
from ..utils.metrics import MetricsLogger, StepRateMeter
from ..utils.profiling import Timer, device_memory_stats
from ..utils.telemetry import Telemetry


def make_eval_fn(apply_fn: Callable, mesh=None, batch_limit: int = 16384):
    """Full-split accuracy like ``accuracy.eval`` (``distributed.py:141-142,148,163``).

    ``apply_fn(params, images) -> logits`` (stateless models).  For models with
    non-trainable state use :func:`make_stateful_eval_fn`.  Returns
    ``evaluate(state, split) -> float`` where ``split`` has ``.images`` /
    ``.labels`` (one-hot).
    """
    return make_stateful_eval_fn(lambda p, ms, x: apply_fn(p, x),
                                 batch_limit=batch_limit)


def make_stateful_eval_fn(eval_logits_fn: Callable, batch_limit: int = 16384):
    """``eval_logits_fn(params, model_state, images) -> logits``.

    Eval batches are sharded over the ``data`` mesh axis (padded to the axis
    size, with a validity mask excluding pad rows), so the full-split
    accuracy pass divides across devices — and across *processes* in
    multi-controller runs — instead of every replica redundantly evaluating
    the whole split.  States without a mesh placement (plain host params in
    unit tests) fall back to unsharded eval.
    """

    @jax.jit
    def _eval_batch(params, model_state, images, labels, valid):
        logits = eval_logits_fn(params, model_state, images)
        hit = (jnp.argmax(logits, -1) == jnp.argmax(labels, -1)) & valid
        return jnp.sum(hit.astype(jnp.int32))

    def evaluate(state, split) -> float:
        from ..parallel.mesh import DATA_AXIS
        from ..parallel.sharding import multihost_replicated_put
        from jax.sharding import NamedSharding, PartitionSpec

        leaves = jax.tree.leaves(state.params)
        mesh = getattr(getattr(leaves[0], "sharding", None), "mesh", None) \
            if leaves else None
        if mesh is not None and DATA_AXIS in mesh.axis_names:
            data_n = mesh.shape[DATA_AXIS]
            sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
            def put(a):
                pad = (-a.shape[0]) % data_n
                if pad:
                    a = np.concatenate(
                        [a, np.zeros((pad,) + a.shape[1:], a.dtype)])
                return jax.device_put(a, sharding)
        else:
            data_n = 1
            put = multihost_replicated_put(state.params)

        images, labels = split.images, split.labels
        model_state = getattr(state, "model_state", None)
        n = images.shape[0]
        correct = 0
        for lo in range(0, n, batch_limit):
            hi = min(lo + batch_limit, n)
            m = hi - lo
            pad_m = m + ((-m) % data_n)
            valid = np.zeros((pad_m,), bool)
            valid[:m] = True
            correct += int(_eval_batch(
                state.params, model_state,
                put(np.asarray(images[lo:hi])),
                put(np.asarray(labels[lo:hi])),
                put(valid)))
        return correct / max(n, 1)

    return evaluate


def _addressable_values(leaf) -> np.ndarray:
    """Host values for histogramming, safe under every placement.

    A jax.Array spanning non-addressable devices (multi-controller TP/PP/EP
    shardings) cannot be fetched whole; histogram this process's addressable
    shards instead — the full tensor when replicated, the local portion when
    sharded (each host logs its own view).  Shards are deduplicated by their
    global index so a replicated parameter (every local device holds a full
    copy) is counted once, not local-device-count times."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        seen: set = set()
        parts = []
        for shard in leaf.addressable_shards:
            key = tuple((s.start, s.stop, s.step) for s in shard.index)
            if key in seen:
                continue
            seen.add(key)
            parts.append(np.asarray(shard.data).ravel())
        return np.concatenate(parts)
    return np.asarray(leaf)


class TrainLoopResult:
    def __init__(self):
        self.local_steps = 0
        self.final_global_step = 0
        self.train_time = 0.0
        self.test_accuracy = None
        self.validation_accuracies: list[tuple[int, float]] = []
        self.last_loss = None
        self.steps_per_sec = 0.0
        self.interrupted = False
        # Elastic membership (training/elastic.py): the loop exited for a
        # checkpoint-reshard-resume cycle — the caller should relaunch
        # into the published cluster spec rather than report completion.
        self.resharded = False


def run_training_loop(
    *,
    state,
    train_step: Callable,
    datasets,
    batch_size: int,
    train_steps: int,
    task_index: int = 0,
    mesh=None,
    batch_sharding=None,
    validation_every: int = 10000,
    log_every: int = 1,
    supervisor=None,
    eval_fn: Callable | None = None,
    replica_mask_fn: Callable[[], Any] | None = None,
    print_fn: Callable[[str], None] = print,
    metrics_logger: MetricsLogger | None = None,
    telemetry: Telemetry | None = None,
    summary_writer=None,
    summary_histograms: bool = False,
    lr_fn: Callable[[int], float] | None = None,
    prefetch: int = 2,
    steps_per_call: int = 1,
    accum_steps: int = 1,
    shutdown=None,
    sharded_feed: bool = False,
    elastic=None,
    stat_publish_fn: Callable[[dict], None] | None = None,
) -> tuple[Any, TrainLoopResult]:
    """Run the reference's training loop shape against a jitted step.

    ``replica_mask_fn`` (optional) supplies the R<N per-replica inclusion mask
    each step, for masked-sync mode.  ``supervisor`` (optional) receives
    ``maybe_save(state)`` after each step — the Supervisor's background
    checkpointing (``distributed.py:109-111``).  ``metrics_logger`` (optional)
    receives a structured record per logged step (SURVEY §5 observability);
    ``summary_writer`` (a :class:`..utils.summary.SummaryWriter`, optional)
    receives the same scalars as TensorBoard events keyed on the global step —
    the Supervisor summary path the reference wired but never used;
    ``summary_histograms`` additionally writes per-parameter weight
    histograms at the validation cadence (needs the writer); ``lr_fn``
    (``optimizer-update-count -> rate``, see
    :func:`..training.optimizers.schedule_from_flags`) surfaces the
    learning rate of each logged step in the metric records and summaries.
    ``prefetch`` stages that many already-device_put batches ahead of the step
    via a background thread (double-buffered host feed; 0 disables).  Note the
    prefetcher pulls up to ``prefetch+1`` batches past the last trained step,
    so the dataset cursor/epoch counter runs slightly ahead; pass
    ``prefetch=0`` if exact cursor position matters across repeated loops on
    one Datasets object.

    ``steps_per_call > 1`` means ``train_step`` is a *scanned* step (see
    :func:`..parallel.sync.build_scanned_sync_train_step`): each call consumes
    a stack of that many batches and advances that many global steps, so
    logging/validation/checkpointing happen at chunk boundaries —
    ``log_every`` and ``validation_every`` must be multiples of it (or 0).
    The loop stacks host batches itself; pass the *stacked* batch sharding.
    The stop check also moves to chunk boundaries, so the loop can overshoot
    ``train_steps`` by up to ``steps_per_call - 1`` optimizer steps — the
    reference's own exit semantics (workers test ``global_step >=
    train_steps`` after the fact and overshoot under concurrency,
    ``distributed.py:155``).

    ``accum_steps > 1`` means ``train_step`` is an *accumulating* step (see
    :func:`..parallel.sync.build_accumulating_sync_train_step`): each call
    consumes that many stacked microbatches but advances ONE optimizer step.
    Mutually exclusive with ``steps_per_call``.

    ``shutdown`` (a :class:`..training.preemption.ShutdownSignal`) makes the
    loop preemption-aware: when the flag latches, the in-flight step
    completes, a final checkpoint is written, and the loop returns with
    ``result.interrupted = True`` (final test eval is skipped — the run is
    expected to resume).

    ``elastic`` (a :class:`..training.elastic.ElasticController`, optional)
    makes the loop membership-aware: its ``on_step`` hook runs once per
    completed step and may hand back a freshly restored state (a worker
    rejoining the replica set) or request a loop exit for a
    checkpoint-reshard-resume cycle (``result.resharded = True``; the
    final test eval is skipped — the run continues in a smaller mesh).

    ``telemetry`` (a :class:`..utils.telemetry.Telemetry`, optional) turns on
    the per-step timing breakdown: host data-wait vs device compute (the
    step dispatch is then synced with ``block_until_ready`` each step, so
    the async-dispatch overlap is traded for honest timing), eval and
    checkpoint pauses as their own kind-tagged records, live MFU, and HBM
    high-watermarks — all flowing into the same JSONL stream as the metric
    records (docs/observability.md documents the schema).  With
    ``steps_per_call``/``accum_steps`` > 1 the "step" being timed is one
    device dispatch (a whole chunk).  When a :mod:`..utils.tracing` tracer
    is installed, the same timings additionally flow as ``kind="span"``
    records (step / data_wait / compute / eval / checkpoint_save), keyed
    on the global step so the exported cross-worker trace correlates the
    same step across hosts.

    ``stat_publish_fn`` (optional) receives one compact per-logged-step
    summary dict (step, loss, step_ms, data_wait_ms, hbm peak) — train.py
    wires it to ``CoordinationClient.stat_put`` so ``tools/watch_run.py``
    can watch the live run.  Publish failures are swallowed: live
    watching must never take training down.
    """
    if steps_per_call < 1:
        raise ValueError(f"steps_per_call must be >= 1, got {steps_per_call}")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if steps_per_call > 1 and accum_steps > 1:
        raise ValueError(
            f"steps_per_call={steps_per_call} and accum_steps={accum_steps} "
            "cannot combine (chunked dispatch of accumulated steps is not "
            "supported); pick one")
    if steps_per_call > 1:
        for name, every in (("log_every", log_every),
                            ("validation_every", validation_every)):
            if every and every % steps_per_call:
                raise ValueError(
                    f"{name}={every} must be a multiple of "
                    f"steps_per_call={steps_per_call} (or 0)")
        if replica_mask_fn is not None:
            raise ValueError(
                "steps_per_call > 1 is incompatible with masked (R<N) sync: "
                "the replica mask is sampled per step")
    result = TrainLoopResult()
    rate_meter = StepRateMeter()
    if eval_fn is None:
        if getattr(state, "model_state", None) is not None:
            raise ValueError(
                "run_training_loop needs an explicit eval_fn for stateful "
                "models (apply_fn signatures differ); use "
                "make_stateful_eval_fn or the model bundle's make_eval_fn().")
        eval_fn = make_eval_fn(state.apply_fn)

    stack_n = steps_per_call if steps_per_call > 1 else accum_steps

    # Multi-controller sharded feed: each process loads ONLY its slice of the
    # global batch (disjoint per-process data streams) and the global array is
    # assembled from process-local rows — host prep cost and feed memory drop
    # by the process count vs every host materializing the full batch.  The
    # reference had the opposite topology: one PS fed by all workers over
    # gRPC (distributed.py:137-145).
    feed_split = datasets.train
    feed_batch_size = batch_size
    shard_feed_active = False
    if sharded_feed and batch_sharding is not None and jax.process_count() > 1:
        pc, pi = jax.process_count(), jax.process_index()
        spec = getattr(batch_sharding, "spec", None)
        seq_sharded = spec is not None and any(
            e not in (None, "data") for e in spec)
        # The feed shards the batch dim over PROCESSES, so the data mesh
        # axis must split evenly across them (data=1 under pure TP/EP, or
        # data < processes, leaves some process's devices spanning the full
        # batch — make_array_from_process_local_data cannot assemble that
        # from per-process slices).
        data_size = mesh.shape.get(mesh_lib.DATA_AXIS, 1) if mesh else 1
        if seq_sharded:
            print_fn(f"Worker {task_index}: sharded feed unavailable with a "
                     "seq-sharded batch layout — feeding full batches")
        elif data_size % pc:
            print_fn(f"Worker {task_index}: sharded feed needs the data "
                     f"mesh axis ({data_size}) divisible by the process "
                     f"count ({pc}) — feeding full batches")
        elif batch_size % pc:
            print_fn(f"Worker {task_index}: sharded feed needs batch_size "
                     f"({batch_size}) divisible by process count ({pc}) — "
                     "feeding full batches")
        elif not hasattr(feed_split, "shard"):
            print_fn(f"Worker {task_index}: train split "
                     f"{type(feed_split).__name__} has no shard() — feeding "
                     "full batches")
        else:
            feed_split = feed_split.shard(pi, pc)
            feed_batch_size = batch_size // pc
            shard_feed_active = True
            print_fn(f"Worker {task_index}: sharded feed — this process "
                     f"loads {feed_batch_size}/{batch_size} examples per "
                     "step")

    # Streaming-corpus resume: restore the feed cursor a previous run saved
    # at its checkpoints, so the restarted run continues near where the
    # lost one stopped (in-memory streams re-derive position from their
    # seeds and need none of this).  The cursor is sampled from the live
    # stream, which the prefetcher has already advanced past the
    # checkpointed step — so it LEADS the weights by up to the prefetch
    # depth and a resumed run skips that many batches rather than
    # repeating any.  For a stochastic stream that is the right bias: no
    # batch is ever trained on twice.
    save_cursor_fn = None
    if supervisor is not None and hasattr(feed_split, "cursor"):
        cursor_path = os.path.join(
            supervisor.logdir, f"data_cursor_p{jax.process_index()}.json")
        if os.path.exists(cursor_path):
            try:
                with open(cursor_path) as fh:
                    ok = feed_split.restore_cursor(json.load(fh))
                print_fn(
                    f"Worker {task_index}: restored streaming-corpus "
                    f"cursor from {cursor_path}" if ok else
                    f"Worker {task_index}: corpus cursor at {cursor_path} "
                    "is from a different stream geometry (fleet size/"
                    "chunking); streaming from the start")
            except (OSError, ValueError, KeyError):
                print_fn(f"Worker {task_index}: unreadable corpus cursor at "
                         f"{cursor_path}; streaming from the start")

        def save_cursor_fn(split=feed_split, path=cursor_path):
            # Written when a checkpoint lands; the live stream has been
            # advanced by the prefetcher, so this cursor LEADS the saved
            # weights by up to the prefetch depth (see note above).
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(split.cursor(), fh)
            os.replace(tmp, path)

    if shard_feed_active:
        batch_dim = 1 if stack_n > 1 else 0
        num_proc = jax.process_count()

        def put(batch):
            def leaf(a):
                a = np.asarray(a)
                gshape = (a.shape[:batch_dim]
                          + (a.shape[batch_dim] * num_proc,)
                          + a.shape[batch_dim + 1:])
                return jax.make_array_from_process_local_data(
                    batch_sharding, a, gshape)
            return jax.tree.map(leaf, batch)
    else:
        def put(batch):
            # Batches are arbitrary pytrees (tuples for image models, dicts
            # for MLM); every leaf is batch-major so one spec shards them all.
            if batch_sharding is None:
                return batch
            return jax.tree.map(lambda a: jax.device_put(a, batch_sharding),
                                batch)

    if stack_n > 1:
        from ..parallel.sync import stack_microbatches

        def host_batch_fn():
            return stack_microbatches(
                [feed_split.next_batch(feed_batch_size)
                 for _ in range(stack_n)])
    else:
        def host_batch_fn():
            return feed_split.next_batch(feed_batch_size)

    prefetcher = None
    observe_produce = (telemetry.histogram("prefetch_produce_ms").record
                       if telemetry is not None else None)
    if prefetch:
        if jax.process_count() > 1:
            # Multi-controller SPMD requires every process to enqueue device
            # work in the same order, so the device_put of the staged batch
            # is issued from the main thread at a fixed point relative to
            # step dispatch; only host-side batch prep runs on a thread.
            # The async transfer still overlaps the in-flight step.
            from ..data.prefetch import StagedPrefetcher
            prefetcher = StagedPrefetcher(host_batch_fn, put, depth=prefetch,
                                          observe_produce_ms=observe_produce)
            print_fn(f"Worker {task_index}: staged prefetch depth={prefetch} "
                     "(multi-controller overlapped feed, main-thread puts)")
        else:
            prefetcher = DevicePrefetcher(host_batch_fn, put, depth=prefetch,
                                          observe_produce_ms=observe_produce)

    try:
        with Timer() as train_timer:
            state = _step_loop(
                state=state, train_step=train_step, datasets=datasets,
                batch_size=batch_size, train_steps=train_steps,
                task_index=task_index, validation_every=validation_every,
                log_every=log_every, supervisor=supervisor, eval_fn=eval_fn,
                replica_mask_fn=replica_mask_fn, print_fn=print_fn,
                metrics_logger=metrics_logger, telemetry=telemetry,
                summary_writer=summary_writer,
                summary_histograms=summary_histograms, lr_fn=lr_fn,
                prefetcher=prefetcher, put=put,
                result=result, rate_meter=rate_meter,
                host_batch_fn=host_batch_fn, steps_per_call=steps_per_call,
                shutdown=shutdown, save_cursor_fn=save_cursor_fn,
                elastic=elastic, stat_publish_fn=stat_publish_fn)
    finally:
        if prefetcher is not None:
            prefetcher.close()

    result.train_time = train_timer.elapsed
    result.steps_per_sec = rate_meter.rate()
    print_fn(f"Training elapsed time:{result.train_time:f} s")

    if result.interrupted:
        print_fn(f"Worker {task_index}: shutdown requested; checkpointing at "
                 f"global step {result.final_global_step} and exiting")
    elif result.resharded:
        print_fn(f"Worker {task_index}: elastic reshard requested; "
                 f"checkpointed at global step {result.final_global_step} "
                 "and exiting for relaunch")
    else:
        test_accuracy = eval_fn(state, datasets.test)
        result.test_accuracy = test_accuracy
        print_fn(f"Worker {task_index}: test accuracy {test_accuracy:g}")
        if summary_writer is not None:
            summary_writer.scalar("accuracy/test", test_accuracy,
                                  result.final_global_step)
            summary_writer.flush()

    if telemetry is not None:
        # One run_summary record closes the stream: histogram quantiles
        # (step/data-wait/compute/eval/checkpoint/barrier), counters, and
        # the headline rates — everything summarize_run needs without
        # replaying the whole stream.
        telemetry.emit_summary(
            step=result.final_global_step,
            local_steps=result.local_steps,
            train_time_s=round(result.train_time, 3),
            steps_per_sec=round(result.steps_per_sec, 3),
            examples_per_sec=round(rate_meter.examples_per_sec(batch_size), 1),
            mfu=telemetry.mfu(result.steps_per_sec),
            interrupted=result.interrupted,
            resharded=result.resharded,
            test_accuracy=result.test_accuracy,
            **({"prefetch": prefetcher.stats()}
               if prefetcher is not None else {}))

    if supervisor is not None:
        if supervisor.maybe_save(state, force=True) and save_cursor_fn:
            save_cursor_fn()
        supervisor.wait_until_finished()
    del mesh
    return state, result


def _hbm_watermark(hbm_peak: dict) -> tuple[int, int, int]:
    """Sample device memory and advance the host-side high-watermark.

    Returns ``(bytes_in_use, peak_bytes, bytes_limit)`` maxed over devices;
    ``peak_bytes`` prefers the allocator's own high-watermark stat and falls
    back to the running max of observed in-use bytes (CPU backends report
    no peak), so the field is monotone either way.
    """
    stats = device_memory_stats()
    in_use = max((d["bytes_in_use"] for d in stats), default=0)
    peak = max((d["peak_bytes_in_use"] for d in stats), default=0)
    limit = max((d["bytes_limit"] for d in stats), default=0)
    hbm_peak["peak"] = max(hbm_peak["peak"], peak, in_use)
    return in_use, hbm_peak["peak"], limit


def _step_loop(*, state, train_step, datasets, batch_size, train_steps,
               task_index, validation_every, log_every, supervisor, eval_fn,
               replica_mask_fn, print_fn, metrics_logger, telemetry,
               summary_writer,
               summary_histograms, lr_fn, prefetcher, put, result, rate_meter,
               host_batch_fn, steps_per_call, shutdown,
               save_cursor_fn=None, elastic=None, stat_publish_fn=None):
    local_step = 0
    metrics = None
    # Telemetry accumulators: per-step timings aggregate between logged
    # records (log_every=1 makes the breakdown truly per-step), histograms
    # keep the whole-run distribution in constant memory.
    data_wait_acc = compute_acc = 0.0
    hbm_peak = {"peak": 0}
    tracer = tracing.active()
    while True:
        wait_t0_unix = time.time()
        t0 = time.perf_counter()
        batch = (prefetcher.next() if prefetcher is not None
                 else put(host_batch_fn()))
        if telemetry is not None:
            data_wait_ms = (time.perf_counter() - t0) * 1000.0
            data_wait_acc += data_wait_ms
            telemetry.histogram("data_wait_ms").record(data_wait_ms)

        if validation_every and local_step % validation_every == 0:
            eval_t0_unix = time.time()
            t0 = time.perf_counter()
            validation_accuracy = eval_fn(state, datasets.validation)
            eval_ms = (time.perf_counter() - t0) * 1000.0
            result.validation_accuracies.append((local_step, validation_accuracy))
            print_fn(f"Worker {task_index}: validation accuracy {validation_accuracy:g}")
            if telemetry is not None:
                telemetry.counter("eval_pauses").inc()
                telemetry.histogram("eval_ms").record(eval_ms)
                if tracer is not None:
                    tracer.emit_span("eval", eval_t0_unix, eval_ms,
                                     step=int(state.global_step))
                # Through the bus (same stream) so the record also lands
                # in the crash flight ring, like train_step/checkpoint —
                # an eval-adjacent death keeps its pause in the dump.
                telemetry.emit("eval", step=int(state.global_step),
                               local_step=local_step,
                               validation_accuracy=validation_accuracy,
                               eval_ms=round(eval_ms, 3))
            elif metrics_logger is not None:
                # Key on the shared global step like the training records (the
                # state already holds it; validation just device-synced anyway).
                metrics_logger.log(int(state.global_step),
                                   local_step=local_step,
                                   validation_accuracy=validation_accuracy)
            if summary_writer is not None:
                summary_writer.scalar("accuracy/validation",
                                      validation_accuracy,
                                      int(state.global_step))
                if summary_histograms:
                    step_now = int(state.global_step)

                    def _histo(path, leaf):
                        summary_writer.histogram(
                            f"params/{path_str(path)}",
                            _addressable_values(leaf), step_now)
                    jax.tree_util.tree_map_with_path(_histo, state.params)
                summary_writer.flush()

        compute_t0_unix = time.time()
        t0 = time.perf_counter()
        if replica_mask_fn is not None:
            state, metrics = train_step(state, batch, replica_mask_fn())
        else:
            state, metrics = train_step(state, batch)
        if telemetry is not None:
            # Honest device-compute time: dispatch -> block-until-ready on
            # the step's outputs.  This trades the async-dispatch overlap
            # for a per-step breakdown — exactly what the telemetry mode
            # is for; leave telemetry off to race the host ahead.
            jax.block_until_ready(metrics)
            compute_ms = (time.perf_counter() - t0) * 1000.0
            compute_acc += compute_ms
            telemetry.histogram("compute_ms").record(compute_ms)
            telemetry.histogram("step_ms").record(data_wait_ms + compute_ms)
            if tracer is not None:
                # Per-step spans, keyed on the global step the dispatch
                # PRODUCED (cheap: block_until_ready already synced, so the
                # scalar fetch is a host copy, not a device wait).  The
                # same trace_id lands on every worker for the same step —
                # the cross-worker correlation the exported trace renders.
                # The step span covers the whole wall interval from batch
                # wait to compute completion — on validation iterations
                # that includes the eval pause, so the eval span nests
                # INSIDE its step instead of overflowing it; data_wait_ms/
                # compute_ms ride in args as the exact breakdown.
                step_now = int(metrics["global_step"])
                tracer.set_step(step_now)
                step_span = tracer.emit_span(
                    "step", wait_t0_unix,
                    (time.time() - wait_t0_unix) * 1000.0,
                    step=step_now,
                    data_wait_ms=round(data_wait_ms, 3),
                    compute_ms=round(compute_ms, 3))
                tracer.emit_span("data_wait", wait_t0_unix, data_wait_ms,
                                 step=step_now, parent_id=step_span)
                tracer.emit_span("compute", compute_t0_unix, compute_ms,
                                 step=step_now, parent_id=step_span)
        local_step += steps_per_call
        rate_meter.update(steps_per_call)

        if supervisor is not None:
            save_t0_unix = time.time()
            t0 = time.perf_counter()
            if supervisor.maybe_save(state):
                if save_cursor_fn is not None:
                    save_cursor_fn()
                if telemetry is not None:
                    save_ms = (time.perf_counter() - t0) * 1000.0
                    telemetry.counter("checkpoints").inc()
                    telemetry.histogram("checkpoint_ms").record(save_ms)
                    telemetry.emit("checkpoint", step=int(metrics["global_step"]),
                                   local_step=local_step,
                                   save_ms=round(save_ms, 3))
                    if tracer is not None:
                        tracer.emit_span("checkpoint_save", save_t0_unix,
                                         save_ms,
                                         step=int(metrics["global_step"]))

        if log_every and local_step % log_every == 0:
            # One host sync per logged step (matches the reference's per-step
            # print, distributed.py:152-153; raise log_every to amortize).
            loss_value = float(metrics["loss"])
            step = int(metrics["global_step"])
            train_accuracy = float(metrics.get("accuracy", float("nan")))
            result.last_loss = loss_value
            print_fn(
                f"Worker {task_index}: traing step {local_step} "
                f"(global step:{step}) loss {loss_value:f} "
                f"training accuracy {train_accuracy:g}")
            extra = ({"grad_norm": float(metrics["grad_norm"])}
                     if "grad_norm" in metrics else {})
            if lr_fn is not None:
                # global_step starts at 1 and increments per update, so the
                # update that produced this step had optax count step - 2.
                extra["learning_rate"] = float(lr_fn(max(step - 2, 0)))
            tele_fields = {}
            stat_payload = None
            if telemetry is not None:
                # The step-time breakdown since the last logged record plus
                # the live utilization/memory view (docs/observability.md).
                # Kept out of ``extra`` — these are stream-only fields
                # (strings/nulls would break the TensorBoard scalars below).
                rate = rate_meter.rate()
                in_use, peak, limit = _hbm_watermark(hbm_peak)
                telemetry.gauge("hbm_peak_bytes").set(peak)
                tele_fields = dict(
                    data_wait_ms=round(data_wait_acc, 3),
                    compute_ms=round(compute_acc, 3),
                    mfu=telemetry.mfu(rate),
                    model_flops_per_sec=telemetry.model_flops_per_sec(rate),
                    hbm_bytes_in_use=in_use,
                    hbm_peak_bytes=peak,
                    hbm_bytes_limit=limit)
                # The live-watching summary (STATPUT): the same breakdown,
                # compact enough for the coordination server's stats ring.
                stat_payload = dict(
                    step=step, loss=round(loss_value, 6),
                    step_ms=round(data_wait_acc + compute_acc, 3),
                    data_wait_ms=round(data_wait_acc, 3),
                    hbm_peak_bytes=peak)
                # Cross-host exchange traffic (docs/param_exchange.md):
                # the averager sets these gauges per exchange period; a
                # worker stuck on the uncompressed path (ratio ~1) is
                # visible live in watch_run and per-worker in
                # summarize_run, not just in a post-mortem.
                exch_bytes = telemetry.gauge("exchange_bytes").value
                exch_ratio = telemetry.gauge("exchange_ratio").value
                if exch_bytes is not None:
                    tele_fields["exchange_bytes"] = int(exch_bytes)
                    stat_payload["exchange_bytes"] = int(exch_bytes)
                if exch_ratio is not None:
                    tele_fields["exchange_ratio"] = round(exch_ratio, 2)
                    stat_payload["exchange_ratio"] = round(exch_ratio, 2)
                # Hierarchical exchange placement (docs/param_exchange.md,
                # "Hierarchical exchange"): the slice this worker reduced
                # in and its inter-host share of the traffic.  A worker
                # silently falling back to the flat exchange publishes
                # neither (the averager clears the gauges to the -1
                # sentinel on flat periods) — which is exactly how
                # watch_run flags it.
                exch_inter = telemetry.gauge("exchange_inter_bytes").value
                exch_slice = telemetry.gauge("exchange_slice").value
                if exch_inter is not None and exch_inter >= 0:
                    tele_fields["inter_bytes"] = int(exch_inter)
                    stat_payload["inter_bytes"] = int(exch_inter)
                if exch_slice is not None and exch_slice >= 0:
                    tele_fields["slice"] = int(exch_slice)
                    stat_payload["slice"] = int(exch_slice)
                data_wait_acc = compute_acc = 0.0
            if telemetry is not None:
                # Route the step record through the bus (same fields, same
                # JSONL stream) so it also lands in the crash flight ring —
                # a killed worker's dump then ends at the step it died on.
                telemetry.emit(
                    "train_step", step=step, local_step=local_step,
                    loss=loss_value, accuracy=train_accuracy,
                    steps_per_sec=round(rate_meter.rate(), 3),
                    examples_per_sec=round(
                        rate_meter.examples_per_sec(batch_size), 1),
                    **extra, **tele_fields)
            elif metrics_logger is not None:
                metrics_logger.log(
                    step, local_step=local_step, loss=loss_value,
                    accuracy=train_accuracy,
                    steps_per_sec=round(rate_meter.rate(), 3),
                    examples_per_sec=round(
                        rate_meter.examples_per_sec(batch_size), 1),
                    **extra)
            if stat_publish_fn is not None and stat_payload is not None:
                try:
                    stat_publish_fn(stat_payload)
                except Exception:
                    # Live watching is best-effort by contract: a dead
                    # coordinator must not take training down.
                    pass
            if summary_writer is not None:
                summary_writer.scalars(
                    {"loss/train": loss_value,
                     "accuracy/train": train_accuracy,
                     "throughput/steps_per_sec": rate_meter.rate(),
                     **extra}, step)
        else:
            step = None

        if step is None:
            step = int(metrics["global_step"])
        # Chaos harness hook: a no-op single check unless an injector is
        # armed (deterministic kill-at-step for the fault-recovery tests).
        faults.on_step(step)
        # Elastic membership hook (runs after faults.on_step so an
        # evict_at_step directive is armed before we look): may hand back
        # a freshly restored state after a rejoin, or request a
        # checkpoint-reshard-resume exit.
        if elastic is not None:
            state, reshard_stop = elastic.on_step(state, step)
            if reshard_stop:
                result.resharded = True
                break
        # Shutdown wins over normal completion: under preemption the hard
        # kill can land during the (slow) final eval, so exit the
        # checkpoint-first path even if train_steps was reached this step.
        if shutdown is not None and shutdown.requested():
            result.interrupted = True
            break
        if step >= train_steps:
            break

    result.local_steps = local_step
    result.final_global_step = step
    return state
