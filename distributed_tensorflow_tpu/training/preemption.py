"""Graceful preemption handling — checkpoint-and-exit on SIGTERM/SIGINT.

TPU pods preempt with a termination signal; the reference's only story was
restart-and-recover (Supervisor checkpoints, ``distributed.py:109-111``).
This module adds the proactive half: a signal flag the training loop polls
each step, so a preempted worker writes a final checkpoint at the exact step
it stopped and exits cleanly instead of dying mid-step and replaying from
the last periodic save.
"""

from __future__ import annotations

import os
import signal
import threading


class ShutdownSignal:
    """Latching signal flag: install as a context manager, poll ``requested``.

    Handlers are installed on ``__enter__`` and restored on ``__exit__``.
    The flag only latches; the loop decides when to act, so a step in
    flight always completes before the checkpoint is written.  By default
    both SIGTERM (pod preemption) and SIGINT (operator Ctrl-C) latch —
    an interactive interrupt deserves the same checkpoint-at-the-exact-step
    exit as a preemption.  ``signal_name`` records which signal fired
    (``"SIGTERM"``/``"SIGINT"``, or ``"trigger"`` for the programmatic
    path) so logs and telemetry can say *why* the run stopped.

    First signal: graceful (latch only).  A second signal while the latch
    is already set restores that signal's previous disposition and
    re-delivers it — a run hung before its next ``requested()`` poll (a
    stuck barrier, a long compile) must stay killable from the terminal,
    not swallow every Ctrl-C until ``__exit__``.

    ``add_callback`` registers hooks that run ONCE, at the moment the flag
    first latches (real signal or programmatic trigger) — the crash
    flight recorder dumps its ring here, so a preempted worker's last
    seconds reach disk even if the graceful checkpoint path never gets to
    run (docs/observability.md, "Flight recorder").  Callbacks run in the
    latching context (possibly a signal handler): they must be quick and
    must not raise — exceptions are swallowed.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._previous: dict = {}
        # True once a REAL signal has latched; escalation keys on this,
        # not on the event — a programmatic trigger() must not turn the
        # next real signal into an immediate kill.
        self._signal_fired = False
        self._callbacks: list = []
        self._callbacks_ran = False
        #: Name of the signal that latched the flag (None until it fires).
        self.signal_name: str | None = None

    def requested(self) -> bool:
        return self._event.is_set()

    def add_callback(self, fn) -> None:
        """Run ``fn()`` once when the shutdown flag first latches."""
        self._callbacks.append(fn)

    def _run_callbacks(self) -> None:
        if self._callbacks_ran:
            return
        self._callbacks_ran = True
        for fn in self._callbacks:
            try:
                fn()
            except Exception:
                pass  # a dying run's hooks don't get to kill the exit path

    def trigger(self) -> None:
        """Programmatic trigger (tests; custom supervisors)."""
        if self.signal_name is None:
            self.signal_name = "trigger"
        self._event.set()
        self._run_callbacks()

    def _handler(self, signum, frame):
        if self._signal_fired:
            # Second signal: the operator means it.  Hand back the previous
            # disposition and re-deliver so a hung run actually dies.
            signal.signal(signum, self._previous.pop(signum, signal.SIG_DFL))
            os.kill(os.getpid(), signum)
            return
        self._signal_fired = True
        try:
            self.signal_name = signal.Signals(signum).name
        except ValueError:  # non-standard signal number
            self.signal_name = f"signal {signum}"
        self._event.set()
        self._run_callbacks()

    def __enter__(self) -> "ShutdownSignal":
        if threading.current_thread() is not threading.main_thread():
            # signal.signal would raise a cryptic "signal only works in main
            # thread of the main interpreter" ValueError; say what the
            # caller should actually do instead.
            raise RuntimeError(
                "ShutdownSignal must be entered on the main thread: Python "
                "delivers signals there and restricts signal.signal to it. "
                "Enter it on the main thread and share the object with "
                "other threads, or drive it via trigger().")
        for sig in self._signals:
            self._previous[sig] = signal.signal(sig, self._handler)
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
