"""Graceful preemption handling — checkpoint-and-exit on SIGTERM.

TPU pods preempt with a termination signal; the reference's only story was
restart-and-recover (Supervisor checkpoints, ``distributed.py:109-111``).
This module adds the proactive half: a signal flag the training loop polls
each step, so a preempted worker writes a final checkpoint at the exact step
it stopped and exits cleanly instead of dying mid-step and replaying from
the last periodic save.
"""

from __future__ import annotations

import signal
import threading


class ShutdownSignal:
    """Latching signal flag: install as a context manager, poll ``requested``.

    Handlers are installed on ``__enter__`` (main thread only — Python
    restricts ``signal.signal`` to it) and restored on ``__exit__``.  The
    flag only latches; the loop decides when to act, so a step in flight
    always completes before the checkpoint is written.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._previous: dict = {}

    def requested(self) -> bool:
        return self._event.is_set()

    def trigger(self) -> None:
        """Programmatic trigger (tests; custom supervisors)."""
        self._event.set()

    def _handler(self, signum, frame):
        self._event.set()

    def __enter__(self) -> "ShutdownSignal":
        if threading.current_thread() is threading.main_thread():
            for sig in self._signals:
                self._previous[sig] = signal.signal(sig, self._handler)
        else:
            # Python restricts signal.signal to the main thread; without
            # handlers the latch can only fire via trigger().  Say so rather
            # than silently losing preemption protection.
            print("WARNING: ShutdownSignal entered off the main thread; "
                  "signal handlers NOT installed (graceful shutdown will "
                  "only react to trigger())")
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
