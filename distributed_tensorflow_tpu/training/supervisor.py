"""Supervision: init-or-recover + checkpointing — the ``tf.train.Supervisor``
equivalent (C9/N6).

Reference behavior matched (``distributed.py:108-131``):
- chief initializes state; non-chiefs poll every ``recovery_wait_secs`` until
  initialization is visible (``prepare_or_wait_for_session``, ``:121-125``);
- state is auto-checkpointed in the background to ``logdir``;
- a restarted process re-enters the same path and recovers.

TPU-native differences (deliberate, documented in SURVEY §5/§7):
- Parameters live in device HBM, not on a surviving PS, so **checkpoints are
  the durability substrate**: recovery = restore latest checkpoint.
- The reference's ``logdir=tempfile.mkdtemp()`` makes resume-across-restart
  effectively impossible (fresh tempdir per process).  We fix that quirk: the
  logdir is a real, stable directory.
- Checkpoints are orbax-based and sharding-aware: each host writes its own
  HBM shards; restore re-lays tensors onto the mesh.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..tools import checkpoint_io

INIT_DONE_KEY = "dtf/initialized"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification where no fallback is
    allowed (an explicitly signaled restore step — restoring anything else
    would break the identical-state invariant across processes)."""


def _pure_tree(state) -> dict:
    """Checkpointable subtree of TrainState (drop apply_fn/tx closures)."""
    tree = {"params": state.params, "opt_state": state.opt_state,
            "global_step": state.global_step}
    model_state = getattr(state, "model_state", None)
    if model_state is not None:
        tree["model_state"] = model_state
    ema = getattr(state, "ema_params", None)
    if ema is not None:
        tree["ema_params"] = ema
    return tree


class Supervisor:
    """Init-or-recover plus background checkpointing.

    Args mirror the reference call
    (``tf.train.Supervisor(is_chief, logdir, init_op, recovery_wait_secs,
    global_step)``, ``distributed.py:110-111``): ``init_fn`` plays ``init_op``;
    the coordination client supplies the cross-process signalling the gRPC
    master provided.
    """

    def __init__(self, is_chief: bool, logdir: str,
                 init_fn: Callable[[], Any],
                 recovery_wait_secs: float = 1.0,
                 save_interval_steps: int = 1000,
                 coordination_client=None,
                 max_to_keep: int = 3):
        self.is_chief = is_chief
        self.logdir = os.path.abspath(logdir)
        self.init_fn = init_fn
        self.recovery_wait_secs = recovery_wait_secs
        self.save_interval_steps = save_interval_steps
        self._coord = coordination_client
        os.makedirs(self.logdir, exist_ok=True)
        self._ckpt_dir = os.path.join(self.logdir, "checkpoints")
        # Retention is applied manually (_apply_retention) rather than via
        # orbax max_to_keep: keep-last-k must never rotate out the newest
        # checkpoint that still PASSES integrity verification — orbax's GC
        # counts checkpoints, not valid ones.
        self.max_to_keep = max_to_keep
        self._mgr = ocp.CheckpointManager(
            self._ckpt_dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None, create=True,
                enable_async_checkpointing=True),
        )
        self._last_saved_step = -1
        # Step whose (async) save has been issued but not yet manifested,
        # and the background thread hashing the previous step's manifest
        # (checksumming a large checkpoint must not stall the step loop).
        self._pending_manifest_step: int | None = None
        self._manifest_thread: threading.Thread | None = None
        #: Recovery events (checkpoint fallbacks, corrupt-skip decisions)
        #: recorded during restore — buffered because restore usually runs
        #: before the telemetry bus exists; ``attach_telemetry`` flushes
        #: them as ``kind="recovery"`` records and wires future ones live.
        self.recovery_events: list[dict] = []
        self._telemetry = None

    # -- recovery telemetry -------------------------------------------------

    def attach_telemetry(self, telemetry) -> None:
        """Route recovery events into a :class:`..utils.telemetry.Telemetry`
        bus; events recorded before attachment (restore runs at startup,
        before the bus exists) are flushed here."""
        self._telemetry = telemetry
        for event in self.recovery_events:
            telemetry.emit("recovery", **event)

    def _record(self, action: str, **fields) -> None:
        event = dict(action=action, **fields)
        self.recovery_events.append(event)
        print(f"Supervisor: recovery event {action}: {fields}")
        if self._telemetry is not None:
            self._telemetry.emit("recovery", **event)

    def _step_dirs(self) -> dict[int, str]:
        return dict(checkpoint_io.list_step_dirs(self._ckpt_dir))

    def _step_dir(self, step: int,
                  dirs: dict[int, str] | None = None) -> str:
        """Step directory; callers looping over steps pass one
        ``_step_dirs()`` snapshot so the directory is listed once per
        operation, not once per step."""
        if dirs is None:
            dirs = self._step_dirs()
        return dirs.get(step, os.path.join(self._ckpt_dir, str(step)))

    # -- init / recovery ----------------------------------------------------

    def prepare_or_wait_for_state(self, timeout: float = 300.0):
        """The ``prepare_or_wait_for_session`` equivalent (``distributed.py:125``).

        Chief: restore latest checkpoint if one exists (crash recovery),
        otherwise run ``init_fn``; then signal readiness.  Non-chief: poll
        until the chief signals (every ``recovery_wait_secs``), then build
        state (same deterministic init, or checkpoint restore) — in
        multi-controller SPMD every process must hold identical state before
        the first collective.
        """
        if jax.process_count() > 1:
            # Multi-controller: orbax restore of global arrays is collective
            # (every process materializes its own shards), so all processes
            # enter restore-or-init together.  The shared checkpoint
            # directory is the coordination signal — every process scans the
            # same latest step; no saves can be in flight at startup.
            state = self._restore_or_init()
            if self.is_chief and self._coord is not None:
                self._coord.kv_set(INIT_DONE_KEY, str(int(state.global_step)))
            return state
        if self.is_chief:
            state = self._restore_or_init()
            if self._coord is not None:
                # Signal the exact step peers must restore (0 = fresh init) so
                # every process holds identical state before the first
                # collective, even if newer checkpoints appear while they join.
                self._coord.kv_set(INIT_DONE_KEY, str(int(state.global_step)))
            return state
        if self._coord is not None:
            value = self._coord.kv_wait(INIT_DONE_KEY, timeout=timeout,
                                        poll_interval=self.recovery_wait_secs)
            signaled = int(value)
            # global_step starts at 1 (reference parity); <=1 means the chief
            # initialized fresh — do NOT restore a (stale) checkpoint then.
            if signaled <= 1:
                return self._restore_or_init(target_step=-1)
            return self._restore_or_init(target_step=self._ckpt_step_for(signaled))
        return self._restore_or_init()

    def _ckpt_step_for(self, global_step: int) -> int | None:
        """Latest checkpoint at or below the signaled global step."""
        steps = [s for s in self._mgr.all_steps() if s <= global_step]
        return max(steps) if steps else None

    def _restore_or_init(self, target_step: int | None = None):
        """target_step: None = restore the newest *valid* checkpoint (corrupt
        ones are skipped with a recovery event — the integrity-fallback
        path); -1 = never restore (fresh init); an int = restore exactly
        that checkpoint step (the chief-signaled step: corruption there
        raises :class:`CheckpointCorruptionError` instead of silently
        restoring something else — see docs/fault_tolerance.md)."""
        state = self.init_fn()
        if target_step == -1:
            return state
        steps = sorted(self._mgr.all_steps())
        if target_step is None:
            candidates = steps[::-1]
        elif target_step not in steps:
            # The chief-signaled step vanished (e.g. the chief's retention
            # raced this process's directory listing).  Fresh init here
            # would silently break the identical-state invariant; fail as
            # loudly as a corrupt signaled step does.
            raise CheckpointCorruptionError(
                f"chief-signaled checkpoint step {target_step} is not on "
                f"disk (available: {steps}); cannot reconstruct the state "
                "the chief holds")
        else:
            candidates = [target_step]
        skipped: list[int] = []
        # Single-controller: full CRC verification.  Multi-controller: every
        # process restores collectively and must reach the SAME step
        # decision, so all use the cheap size-only check (identical,
        # deterministic inputs; catches truncation, the dominant corruption
        # mode) — full-hashing would also re-read the entire checkpoint
        # once per process over shared storage.
        full_verify = jax.process_count() == 1
        dirs = self._step_dirs()
        for step in candidates:
            status, detail = checkpoint_io.verify_checkpoint(
                self._step_dir(step, dirs), full=full_verify)
            if status == "corrupt":
                if target_step is not None:
                    raise CheckpointCorruptionError(
                        f"checkpoint step {step} (the chief-signaled restore "
                        f"point) failed integrity verification: {detail}")
                self._record("checkpoint_corrupt", step=step, detail=detail)
                skipped.append(step)
                continue
            state = self._restore_step(state, step)
            if skipped:
                self._record("checkpoint_fallback", step=step,
                             skipped=skipped,
                             detail=f"restored step {step}; newer "
                                    f"checkpoint(s) {skipped} corrupt")
                self._purge_corrupt(skipped)
            return state
        if skipped:
            # Every checkpoint on disk failed verification: fresh init is
            # the only remaining recovery, and it must be loud.
            self._record("checkpoint_restore_failed", skipped=skipped,
                         detail="no valid checkpoint found; fresh init")
            self._purge_corrupt(skipped)
        return state

    def _purge_corrupt(self, steps: list[int]) -> None:
        """Delete corrupt checkpoints the restore fell back past.  They are
        dead bytes — and leaving them makes the on-disk step sequence
        non-monotonic for orbax, which silently skips saving any step below
        the latest on disk: the run's first post-fallback periodic save
        would be dropped.  The corruption detail survives in the recovery
        records."""
        for step in steps:
            if self._delete_step(step):
                self._record("corrupt_checkpoint_deleted", step=step)

    def _delete_step(self, step: int) -> bool:
        """Collective-safe checkpoint deletion.  Orbax's ``delete`` is a
        multihost *collective* (every process must enter it or process 0
        stalls on a 360 s barrier), so multi-controller callers reach here
        on every process with identical, deterministic arguments; in
        single-controller runs only the chief (the sole saver over the
        shared logdir) deletes."""
        if jax.process_count() == 1 and not self.is_chief:
            return False
        try:
            self._mgr.delete(step)
            return True
        except Exception as e:  # never let GC take training down
            self._record("retention_delete_failed", step=step,
                         detail=str(e))
            return False

    def _restore_step(self, state, step: int):
        """Restore one verified step into ``state`` (orbax errors propagate:
        a *structure* mismatch is a configuration problem, not corruption —
        eval mode turns it into flag advice)."""
        target = _pure_tree(state)
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(_abstract(target)))
        except ValueError:
            # Structure mismatch: --ema_decay was toggled between runs.
            # Retry with the EMA key flipped — a checkpoint without
            # ``ema_params`` restores into an EMA-enabled run (the
            # average is re-seeded below), and one WITH it restores into
            # an EMA-disabled run (the saved average is dropped).
            if "ema_params" in target:
                alt = {k: v for k, v in target.items()
                       if k != "ema_params"}
            else:
                alt = dict(target, ema_params=target["params"])
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(_abstract(alt)))
        state = state.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            global_step=restored["global_step"],
        )
        if "model_state" in restored:
            state = state.replace(model_state=restored["model_state"])
        if getattr(state, "ema_params", None) is not None:
            # EMA active this run: adopt the saved average, or — when the
            # checkpoint predates EMA — re-seed it from the restored
            # weights (a copy: donation must never alias params).
            ema = restored.get("ema_params")
            if ema is None:
                ema = jax.tree.map(lambda x: x.copy(), restored["params"])
            state = state.replace(ema_params=ema)
        return state

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def refresh(self) -> None:
        """Re-scan the checkpoint directory.  Orbax caches the step listing
        at manager construction; a mid-run rejoiner must see the saves
        other processes landed while it was out of the replica set."""
        reload_fn = getattr(self._mgr, "reload", None)
        if reload_fn is not None:
            reload_fn()

    def restore_for_rejoin(self, timeout: float = 60.0):
        """Elastic-rejoin restore (docs/fault_tolerance.md, "Elastic
        membership"): a worker re-admitted to the replica set must discard
        the weights it held while masked out — the survivors kept training
        past them — and adopt the cluster's latest durable state.  Re-scans
        the directory, then restores the chief's signaled step when a
        coordination client is attached (the chief re-publishes the
        init-done key at every durable save), else the newest valid
        checkpoint."""
        # Settle any in-flight async save first (chief rejoining after a
        # transient self-eviction): orbax cannot restore around a pending
        # save, and the finalize also refreshes the published init signal.
        self.wait_until_finished()
        # The chief keeps saving AND rotating checkpoints while we restore:
        # a directory scan can stat a step retention just deleted, and a
        # signaled step can vanish right after we read the signal.  Both
        # are races, not corruption — re-scan and retry within the budget.
        deadline = time.monotonic() + timeout
        while True:
            try:
                self.refresh()
                if not self.is_chief and self._coord is not None:
                    value = self._coord.kv_wait(
                        INIT_DONE_KEY, timeout=timeout,
                        poll_interval=self.recovery_wait_secs)
                    signaled = int(value)
                    if signaled <= 1:
                        # Nothing durable yet (the chief initialized fresh
                        # and has not saved): re-derive the deterministic
                        # init — the best reconstruction of the chief's
                        # lineage available.
                        return self._restore_or_init(target_step=-1)
                    return self._restore_or_init(
                        target_step=self._ckpt_step_for(signaled))
                return self._restore_or_init()
            except (FileNotFoundError, CheckpointCorruptionError) as e:
                if time.monotonic() >= deadline:
                    raise
                self._record("rejoin_restore_retry", detail=str(e)[:200])
                time.sleep(self.recovery_wait_secs)

    # -- checkpointing ------------------------------------------------------

    def maybe_save(self, state, force: bool = False) -> bool:
        """Chief-driven periodic checkpoint (Supervisor background-save parity).

        Single-controller: non-chiefs never save.  Multi-controller
        (``jax.process_count() > 1``): orbax writes global arrays
        *collectively*, so every process must enter ``save`` — the steps are
        lockstep in SPMD, hence all processes reach the same save cadence.
        """
        if not self.is_chief and jax.process_count() == 1:
            return False
        step = int(state.global_step)
        if not force and (step - self._last_saved_step) < self.save_interval_steps:
            return False
        # Finalize the PREVIOUS async save (manifest + retention) before
        # issuing the next one: the manifest must only ever describe a
        # finished checkpoint, and deferring it one save keeps the async
        # overlap (save N runs under step N+1's compute; its manifest
        # lands when save N+1 is issued, or at wait/close).
        self._finalize_last_save()
        self._mgr.save(step, args=ocp.args.StandardSave(_pure_tree(state)))
        self._pending_manifest_step = step
        self._last_saved_step = step
        return True

    def _finalize_last_save(self) -> None:
        """Wait out the in-flight save, start its integrity manifest
        (atomic finalize, on a background thread — re-hashing a large
        checkpoint must not stall the step loop), and apply retention.
        Manifest + retention run on process 0 only: in multi-controller
        runs every process enters ``save`` collectively, but the shared
        directory needs one writer."""
        if self._pending_manifest_step is None:
            return
        self._mgr.wait_until_finished()
        step = self._pending_manifest_step
        self._pending_manifest_step = None
        if self.is_chief and self._coord is not None:
            # Re-publish the init signal at every durable save: a non-chief
            # incarnation rejoining mid-run then pins its restore to the
            # cluster's LATEST durable step, not the step the chief held at
            # startup (which retention may long since have rotated away).
            try:
                self._coord.kv_set(INIT_DONE_KEY, str(step))
            except Exception:  # a signal refresh must never kill training
                pass
        if jax.process_index() == 0:
            self._join_manifest_thread()  # at most one manifest in flight
            step_dir = self._step_dir(step)

            def hash_and_write():
                try:
                    checkpoint_io.write_manifest(step_dir)
                except OSError as e:
                    # An unmanifested checkpoint is merely *unverified*.
                    self._record("manifest_write_failed", step=step,
                                 detail=str(e))
            self._manifest_thread = threading.Thread(target=hash_and_write,
                                                     daemon=True)
            self._manifest_thread.start()
        # Retention runs on EVERY process (orbax delete is a collective;
        # see _delete_step) and only quick-verifies (sizes, no hashing):
        # a mid-write manifest reads as "unverified", which retention
        # treats as non-corrupt — never a deletion trigger — so all
        # processes reach the same keep-set.
        self._apply_retention()

    def _join_manifest_thread(self) -> None:
        if self._manifest_thread is not None:
            self._manifest_thread.join()
            self._manifest_thread = None

    def _apply_retention(self) -> None:
        """Keep the last ``max_to_keep`` checkpoints — plus, always, the
        newest one that passes (quick) integrity verification, so rotation
        can never delete the only restorable state while newer saves are
        corrupt.  ``max_to_keep`` of 0/None keeps everything."""
        if not self.max_to_keep or self.max_to_keep <= 0:
            return
        steps = sorted(self._mgr.all_steps())
        if len(steps) <= self.max_to_keep:
            return
        keep = set(steps[-self.max_to_keep:])
        dirs = self._step_dirs()
        for step in reversed(steps):
            status, _ = checkpoint_io.verify_checkpoint(
                self._step_dir(step, dirs), full=False)
            if status != "corrupt":
                keep.add(step)  # newest non-corrupt survives rotation
                break
        for step in steps:
            if step not in keep:
                self._delete_step(step)

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()
        self._finalize_last_save()
        self._join_manifest_thread()

    def close(self) -> None:
        self.wait_until_finished()
        self._mgr.close()


def _abstract(tree):
    """Shape/dtype/sharding skeleton for orbax StandardRestore."""
    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return jax.tree.map(leaf, tree)
