"""Supervision: init-or-recover + checkpointing — the ``tf.train.Supervisor``
equivalent (C9/N6).

Reference behavior matched (``distributed.py:108-131``):
- chief initializes state; non-chiefs poll every ``recovery_wait_secs`` until
  initialization is visible (``prepare_or_wait_for_session``, ``:121-125``);
- state is auto-checkpointed in the background to ``logdir``;
- a restarted process re-enters the same path and recovers.

TPU-native differences (deliberate, documented in SURVEY §5/§7):
- Parameters live in device HBM, not on a surviving PS, so **checkpoints are
  the durability substrate**: recovery = restore latest checkpoint.
- The reference's ``logdir=tempfile.mkdtemp()`` makes resume-across-restart
  effectively impossible (fresh tempdir per process).  We fix that quirk: the
  logdir is a real, stable directory.
- Checkpoints are orbax-based and sharding-aware: each host writes its own
  HBM shards; restore re-lays tensors onto the mesh.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import jax
import numpy as np
import orbax.checkpoint as ocp

INIT_DONE_KEY = "dtf/initialized"


def _pure_tree(state) -> dict:
    """Checkpointable subtree of TrainState (drop apply_fn/tx closures)."""
    tree = {"params": state.params, "opt_state": state.opt_state,
            "global_step": state.global_step}
    model_state = getattr(state, "model_state", None)
    if model_state is not None:
        tree["model_state"] = model_state
    ema = getattr(state, "ema_params", None)
    if ema is not None:
        tree["ema_params"] = ema
    return tree


class Supervisor:
    """Init-or-recover plus background checkpointing.

    Args mirror the reference call
    (``tf.train.Supervisor(is_chief, logdir, init_op, recovery_wait_secs,
    global_step)``, ``distributed.py:110-111``): ``init_fn`` plays ``init_op``;
    the coordination client supplies the cross-process signalling the gRPC
    master provided.
    """

    def __init__(self, is_chief: bool, logdir: str,
                 init_fn: Callable[[], Any],
                 recovery_wait_secs: float = 1.0,
                 save_interval_steps: int = 1000,
                 coordination_client=None,
                 max_to_keep: int = 3):
        self.is_chief = is_chief
        self.logdir = os.path.abspath(logdir)
        self.init_fn = init_fn
        self.recovery_wait_secs = recovery_wait_secs
        self.save_interval_steps = save_interval_steps
        self._coord = coordination_client
        os.makedirs(self.logdir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            os.path.join(self.logdir, "checkpoints"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True),
        )
        self._last_saved_step = -1

    # -- init / recovery ----------------------------------------------------

    def prepare_or_wait_for_state(self, timeout: float = 300.0):
        """The ``prepare_or_wait_for_session`` equivalent (``distributed.py:125``).

        Chief: restore latest checkpoint if one exists (crash recovery),
        otherwise run ``init_fn``; then signal readiness.  Non-chief: poll
        until the chief signals (every ``recovery_wait_secs``), then build
        state (same deterministic init, or checkpoint restore) — in
        multi-controller SPMD every process must hold identical state before
        the first collective.
        """
        if jax.process_count() > 1:
            # Multi-controller: orbax restore of global arrays is collective
            # (every process materializes its own shards), so all processes
            # enter restore-or-init together.  The shared checkpoint
            # directory is the coordination signal — every process scans the
            # same latest step; no saves can be in flight at startup.
            state = self._restore_or_init()
            if self.is_chief and self._coord is not None:
                self._coord.kv_set(INIT_DONE_KEY, str(int(state.global_step)))
            return state
        if self.is_chief:
            state = self._restore_or_init()
            if self._coord is not None:
                # Signal the exact step peers must restore (0 = fresh init) so
                # every process holds identical state before the first
                # collective, even if newer checkpoints appear while they join.
                self._coord.kv_set(INIT_DONE_KEY, str(int(state.global_step)))
            return state
        if self._coord is not None:
            value = self._coord.kv_wait(INIT_DONE_KEY, timeout=timeout,
                                        poll_interval=self.recovery_wait_secs)
            signaled = int(value)
            # global_step starts at 1 (reference parity); <=1 means the chief
            # initialized fresh — do NOT restore a (stale) checkpoint then.
            if signaled <= 1:
                return self._restore_or_init(target_step=-1)
            return self._restore_or_init(target_step=self._ckpt_step_for(signaled))
        return self._restore_or_init()

    def _ckpt_step_for(self, global_step: int) -> int | None:
        """Latest checkpoint at or below the signaled global step."""
        steps = [s for s in self._mgr.all_steps() if s <= global_step]
        return max(steps) if steps else None

    def _restore_or_init(self, target_step: int | None = None):
        """target_step: None = restore latest; -1 = never restore (fresh init);
        an int = restore exactly that checkpoint step."""
        state = self.init_fn()
        if target_step == -1:
            return state
        step = self._mgr.latest_step() if target_step is None else target_step
        if step is not None:
            target = _pure_tree(state)
            try:
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(_abstract(target)))
            except ValueError:
                # Structure mismatch: --ema_decay was toggled between runs.
                # Retry with the EMA key flipped — a checkpoint without
                # ``ema_params`` restores into an EMA-enabled run (the
                # average is re-seeded below), and one WITH it restores into
                # an EMA-disabled run (the saved average is dropped).
                if "ema_params" in target:
                    alt = {k: v for k, v in target.items()
                           if k != "ema_params"}
                else:
                    alt = dict(target, ema_params=target["params"])
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(_abstract(alt)))
            state = state.replace(
                params=restored["params"],
                opt_state=restored["opt_state"],
                global_step=restored["global_step"],
            )
            if "model_state" in restored:
                state = state.replace(model_state=restored["model_state"])
            if getattr(state, "ema_params", None) is not None:
                # EMA active this run: adopt the saved average, or — when the
                # checkpoint predates EMA — re-seed it from the restored
                # weights (a copy: donation must never alias params).
                ema = restored.get("ema_params")
                if ema is None:
                    ema = jax.tree.map(lambda x: x.copy(), restored["params"])
                state = state.replace(ema_params=ema)
        return state

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    # -- checkpointing ------------------------------------------------------

    def maybe_save(self, state, force: bool = False) -> bool:
        """Chief-driven periodic checkpoint (Supervisor background-save parity).

        Single-controller: non-chiefs never save.  Multi-controller
        (``jax.process_count() > 1``): orbax writes global arrays
        *collectively*, so every process must enter ``save`` — the steps are
        lockstep in SPMD, hence all processes reach the same save cadence.
        """
        if not self.is_chief and jax.process_count() == 1:
            return False
        step = int(state.global_step)
        if not force and (step - self._last_saved_step) < self.save_interval_steps:
            return False
        self._mgr.save(step, args=ocp.args.StandardSave(_pure_tree(state)))
        self._last_saved_step = step
        return True

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def _abstract(tree):
    """Shape/dtype/sharding skeleton for orbax StandardRestore."""
    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return jax.tree.map(leaf, tree)
