"""Cells — fleet-of-fleets behind one global router
(docs/serving.md, "Cells").

PR 12's :class:`..serving.router.Router` made N replicas one endpoint;
PR 13/15 made the coordinator plane shardable and highly available.
But the composition was still ONE failure domain: a coordinator-plane
meltdown, an autoscale flap, or an abusive tenant hits every user at
once.  A **cell** is the isolation unit above the fleet: one
coordinator plane (with its warm standby) + one fleet router + N
replicas, launched as a unit (``tools/serve_cell.py``).  The
:class:`GlobalRouter` here fronts M cells and speaks the SAME wire
format a single server or a fleet router does (``POST /generate`` /
``GET /healthz`` / ``/statz``), so every existing
:class:`..serving.client.ServeClient` caller works unchanged — the
PR-12 router composing with itself, one level up.

Three policies, deliberately reusing the fleet router's pure pieces:

- **Tenant homes** — every tenant is *homed* on exactly one cell
  (sticky: decode-state locality, fairness books, and SLO windows all
  live in one cell).  Selection reuses :func:`..serving.router
  .choose_replica` with the home map as the affinity map and a HIGH
  spill margin: unlike replica affinity, a tenant leaving its home
  cell is an isolation event, not a load-balancing nicety.  The home
  map is persisted to EVERY reachable cell's coordination KV plane
  (seq-versioned, newest wins at recovery) so it survives both a
  global-router restart and the loss of any cell.
- **Cell failover** — a cell's router ``/healthz`` + ``/fleetz`` is
  the unit of aliveness.  ``fail_after`` consecutive probe failures
  (or a ``503 no_healthy_replica``) marks the cell dead: its tenants
  are re-homed onto surviving cells immediately, its in-flight
  forwards fail over with the PR-12 one-response guarantee (transport
  error → retry elsewhere; timeout → 503, NEVER re-sent), and the
  first re-homed request that completes records the **failover gap**
  (wall time from death to first served request) as ``kind="cell"``
  telemetry.  A cell that sustains SLO burn for ``burn_fail_s`` gets
  the same tenant re-home without being declared dead.  On recovery,
  ``rehome_policy`` decides: ``"sticky"`` leaves tenants where they
  landed; ``"return"`` sends displaced tenants back to their origin.
- **Blast radius** — failover load must not cascade: a dead cell's
  tenants arriving on the survivor could push IT into burn, and the
  next failover takes the whole tier down.  :class:`AdmissionThrottle`
  bounds each re-homed tenant to a small in-flight budget (the
  ``FairScheduler`` bound vocabulary: per-tenant cap, ``QueueFull`` →
  429) for a decaying window after the re-home.  Excess arrives as
  429 backpressure AT THE GLOBAL ROUTER — the surviving cell never
  sees it.

Telemetry: ``kind="cell"`` records (membership, ``cell_dead``,
``tenant_rehome``/``tenant_return``, ``failover_gap``,
``throttle_reject``, periodic ``poll``) — ``tools/summarize_run.py``
rolls them into a cells section and ``--check`` enforces the field
contract (``REQUIRED_CELL_FIELDS``); ``tools/watch_serve.py --cells``
renders the live global table from ``/cellz``.

The policy pieces (:func:`cell_load`, :class:`AdmissionThrottle`) are
pure and clock-injectable — unit-tested without sockets in
tests/test_cells.py.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
import urllib.error
import urllib.request

from .router import choose_replica
from .scheduler import QueueFull, TenantConfig
from ..utils import tracing

#: Cell lifecycle: added -(healthz+fleetz ok)-> healthy
#: -(fail_after probes / no_healthy_replica)-> dead -(probe ok)-> healthy.
CELL_STATES = ("starting", "healthy", "dead")

#: States a new request may be routed to.
ROUTABLE_CELL_STATES = ("healthy",)

#: KV key the tenant-home map persists under, on every cell's plane.
HOME_KEY = "cells/tenant_homes"


# ---------------------------------------------------------- cell policy


def cell_load(statz: dict | None) -> float:
    """One cell's load figure from its fleet router's ``/statz``.

    Same shape as :func:`..serving.router.replica_load`, one level up:
    fleet-wide queue depth dominates (queued work is waiting NOW);
    active decode slots per healthy replica break ties among
    empty-queue cells.  A cell with no snapshot yet scores 0 (a
    freshly adopted cell should attract load)."""
    if not statz:
        return 0.0
    queue = statz.get("queue_depth") or 0
    healthy = statz.get("healthy") or 1
    active = (statz.get("active_slots") or 0) / max(1, healthy)
    return 2.0 * float(queue) + float(active)


class AdmissionThrottle:
    """Blast-radius bound for re-homed traffic.

    When a cell dies, its tenants' full arrival rate lands on the
    survivors at once — exactly the flash crowd that could cascade a
    second cell into SLO burn.  This throttle caps each *recently
    re-homed* tenant to ``bound`` concurrently in-flight requests
    through the global router for ``window_s`` seconds after its
    re-home; excess raises :class:`..serving.scheduler.QueueFull`
    (surfaced as HTTP 429, the scheduler's own backpressure verb)
    WITHOUT ever reaching the surviving cell.  Tenants outside the
    window pass untouched — steady-state traffic is never throttled.

    Per-tenant overrides reuse :class:`..serving.scheduler
    .TenantConfig`: ``max_queue`` is read as the in-flight cap
    (``tools/serve_cell.py --rehome_tenants`` feeds ``parse_tenants``
    output straight in).  Pure and clock-injectable."""

    def __init__(self, *, bound: int = 4, window_s: float = 30.0,
                 tenants: list[TenantConfig] | None = None,
                 clock=time.monotonic):
        if bound < 1:
            raise ValueError(f"bound must be >= 1, got {bound}")
        self.bound = int(bound)
        self.window_s = float(window_s)
        self._bounds = {t.name: t.max_queue for t in (tenants or [])}
        self._clock = clock
        self._lock = threading.Lock()
        self._rehomed_at: dict[str, float] = {}
        self._in_flight: dict[str, int] = {}
        self._admitted = 0
        self._rejected = 0

    def bound_for(self, tenant: str) -> int:
        return self._bounds.get(tenant, self.bound)

    def mark_rehomed(self, tenant: str) -> None:
        """Open (or refresh) the throttle window for ``tenant``."""
        with self._lock:
            self._rehomed_at[tenant] = self._clock()

    def throttled(self, tenant: str) -> bool:
        """Is ``tenant`` inside its re-home window?  (Expires lazily.)"""
        with self._lock:
            return self._throttled_locked(tenant)

    def _throttled_locked(self, tenant: str) -> bool:
        at = self._rehomed_at.get(tenant)
        if at is None:
            return False
        if self._clock() - at >= self.window_s:
            del self._rehomed_at[tenant]
            return False
        return True

    def acquire(self, tenant: str) -> bool:
        """Take an in-flight token for a throttled tenant.

        Returns ``False`` when the tenant is not under throttle (no
        token taken, no release owed), ``True`` on a taken token, and
        raises :class:`QueueFull` at the bound — the caller answers
        429 without forwarding anything."""
        with self._lock:
            if not self._throttled_locked(tenant):
                return False
            bound = self.bound_for(tenant)
            if self._in_flight.get(tenant, 0) >= bound:
                self._rejected += 1
                raise QueueFull(
                    f"tenant {tenant!r} re-home throttle full "
                    f"({bound} in flight); retry with backoff")
            self._in_flight[tenant] = self._in_flight.get(tenant, 0) + 1
            self._admitted += 1
            return True

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._in_flight.get(tenant, 0)
            if n <= 1:
                self._in_flight.pop(tenant, None)
            else:
                self._in_flight[tenant] = n - 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "bound": self.bound,
                "window_s": self.window_s,
                "throttled_tenants": sorted(
                    t for t in self._rehomed_at
                    if self._clock() - self._rehomed_at[t]
                    < self.window_s),
                "in_flight": dict(self._in_flight),
                "admitted": self._admitted,
                "rejected": self._rejected,
            }


# ----------------------------------------------------------- membership


class CellHandle:
    """One cell as the global router sees it: the fleet router URL (the
    wire surface), the coordination-plane spec (the persistence
    surface), and the latest probe snapshot."""

    def __init__(self, name: str, url: str, *, coord: str | None = None,
                 state: str = "starting"):
        assert state in CELL_STATES, state
        self.name = name
        self.url = url.rstrip("/")
        self.coord = coord          # "host:port[,host:port]" KV spec
        self.state = state
        self.statz: dict | None = None   # fleet router /statz snapshot
        self.members: list[dict] = []    # trimmed /fleetz member views
        self.burning: list[str] = []     # fleet-wide burning objectives
        self.burn_since: float | None = None
        self.burn_rehomed = False
        self.fails = 0
        self.in_flight = 0
        self.routed = 0
        self.served = 0
        self.t_added = time.time()
        self.t_dead: float | None = None
        self.dead_reason = ""
        self.t_statz: float | None = None   # monotonic, last statz refresh

    def view(self) -> dict:
        statz = self.statz or {}
        return {
            "cell": self.name,
            "url": self.url,
            "coord": self.coord,
            "state": self.state,
            "load": round(cell_load(self.statz) + self.in_flight, 3),
            "replicas": statz.get("replicas"),
            "healthy": statz.get("healthy"),
            "queue_depth": statz.get("queue_depth"),
            "active_slots": statz.get("active_slots"),
            "in_flight": self.in_flight,
            "routed": self.routed,
            "served": self.served,
            "burning": list(self.burning),
            "fails": self.fails,
            "dead_reason": self.dead_reason,
            "statz": statz,
        }


# -------------------------------------------------------- global router


class GlobalRouter:
    """The cell frontend.  ``add_cell()`` members, ``recover_homes()``
    (optional), ``start()``, ``shutdown()``.  See the module docstring
    for the three policies."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 telemetry=None, poll_s: float = 1.0,
                 fail_after: int = 2, spill_margin: float = 50.0,
                 request_timeout_s: float = 120.0,
                 rehome_policy: str = "sticky",
                 throttle: AdmissionThrottle | None = None,
                 burn_fail_s: float = 0.0,
                 boot_timeout_s: float = 600.0,
                 cell_emit_every_s: float = 2.0,
                 home_key: str = HOME_KEY):
        if rehome_policy not in ("sticky", "return"):
            raise ValueError(
                f"rehome_policy must be 'sticky' or 'return', "
                f"got {rehome_policy!r}")
        self.telemetry = telemetry
        self.poll_s = float(poll_s)
        self.fail_after = int(fail_after)
        self.spill_margin = float(spill_margin)
        self.request_timeout_s = float(request_timeout_s)
        self.rehome_policy = rehome_policy
        self.throttle = throttle
        self.burn_fail_s = float(burn_fail_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.cell_emit_every_s = float(cell_emit_every_s)
        self.home_key = home_key
        self._lock = threading.Lock()
        self._cells: dict[str, CellHandle] = {}
        self._homes: dict[str, str] = {}     # tenant -> cell name
        self._origin: dict[str, str] = {}    # displaced tenant -> origin
        self._home_seq = 0
        self._homes_dirty = False
        self._gap_pending: dict[str, float] = {}   # dead cell -> t_dead
        self._kv_clients: dict[str, Any] = {}
        self._routed_total = 0
        self._served_total = 0
        self._failed_total = 0
        self._failover_total = 0
        self._spill_total = 0
        self._rehome_total = 0
        self._return_total = 0
        self._throttle_rejected = 0
        self._max_gap_ms = 0.0
        self._ticks = 0
        self._last_cell_emit = 0.0
        self._stop = threading.Event()
        self._control: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._host, self._port = host, int(port)

    # ------------------------------------------------------- membership

    def add_cell(self, name: str, url: str, *, coord: str | None = None,
                 state: str = "starting") -> str:
        """Adopt a cell by its fleet-router URL.  ``coord`` is the
        cell's coordination-plane spec (``host:port[,standby...]``, or
        ``;``-separated per-instance groups for a sharded plane — see
        :meth:`_kv_client`) — cells without one still serve, but cannot
        mirror the tenant home map.  New cells start in ``starting``
        and attract traffic once a health probe promotes them."""
        with self._lock:
            if name in self._cells:
                raise ValueError(f"duplicate cell {name!r}")
            self._cells[name] = CellHandle(name, url, coord=coord,
                                           state=state)
        return name

    def _mark_cell_dead_locked(self, c: CellHandle, reason: str) -> None:
        """Lock held.  End the cell's routing eligibility and queue its
        tenants for re-home; in-flight forwards fail over on their
        own.  The failover-gap clock starts HERE."""
        c.state = "dead"
        c.dead_reason = reason[:300]
        c.t_dead = time.time()
        c.burn_since = None
        c.burn_rehomed = False
        self._gap_pending[c.name] = c.t_dead

    # ---------------------------------------------- tenant-home persist

    def _kv_client(self, name: str, coord: str):
        """A (cached) observer client onto one cell's KV plane — never
        registers as a task, small retry budget so a dead plane costs
        the control loop little.

        Two spec forms (docs/fault_tolerance.md, "KV-shard HA"):
        ``"h:p[,h:p]"`` — one instance's ordered endpoint list (primary
        first, then its warm standbys; the observer walks it on
        failure); ``"h0:p0[,standby];h1:p1[,standby]"`` — a SHARDED
        plane, one ``;``-segment per instance, each with its own
        standby tail.  Either way a home-mirror read/write rides a
        shard failover instead of dropping the mirror."""
        client = self._kv_clients.get(name)
        if client is not None:
            return client
        if ";" in coord:
            from ..cluster.coordination import CoordinationRouter
            primaries, standbys = [], {}
            for i, seg in enumerate(s for s in coord.split(";") if s):
                head, _, tail = seg.partition(",")
                primaries.append(head)
                if tail:
                    standbys[i] = tail
            client = CoordinationRouter.observer(
                ",".join(primaries), retry_budget=2.0,
                standbys=standbys or None)
        else:
            from ..cluster.coordination import CoordinationClient
            client = CoordinationClient.observer(coord, retry_budget=2.0)
        self._kv_clients[name] = client
        return client

    def _home_payload_locked(self) -> str:
        return json.dumps(
            {"seq": self._home_seq, "homes": self._homes,
             "origin": self._origin},
            separators=(",", ":"), sort_keys=True)

    def flush_homes(self) -> int:
        """Mirror the home map to every cell that has a KV plane.
        Best-effort per cell (a dead plane is exactly the event the
        mirroring exists to survive); returns the number of planes
        written.  Runs on the control thread — never the route path."""
        with self._lock:
            if not self._homes_dirty:
                return 0
            payload = self._home_payload_locked()
            targets = [(c.name, c.coord) for c in self._cells.values()
                       if c.coord and c.state != "dead"]
            self._homes_dirty = False
        written = 0
        for name, coord in targets:
            try:
                self._kv_client(name, coord).kv_set(self.home_key,
                                                    payload)
                written += 1
            except Exception:  # noqa: BLE001 — mirrored, best-effort
                self._kv_clients.pop(name, None)
        return written

    def recover_homes(self) -> int:
        """Read the home map back from every reachable cell's KV plane;
        the highest ``seq`` wins (a stale mirror on a cell that was
        dead during recent re-homes must not roll them back).  Returns
        the adopted seq (0 when nothing was found)."""
        with self._lock:
            targets = [(c.name, c.coord) for c in self._cells.values()
                       if c.coord]
        best: dict | None = None
        for name, coord in targets:
            try:
                raw = self._kv_client(name, coord).kv_get(self.home_key)
            except Exception:  # noqa: BLE001 — unreachable plane: skip
                self._kv_clients.pop(name, None)
                continue
            if not raw:
                continue
            try:
                doc = json.loads(raw)
            except ValueError:
                continue
            if best is None or doc.get("seq", 0) > best.get("seq", 0):
                best = doc
        if best is None:
            return 0
        with self._lock:
            self._home_seq = int(best.get("seq", 0))
            self._homes = {str(t): str(c)
                           for t, c in (best.get("homes") or {}).items()}
            self._origin = {str(t): str(c)
                            for t, c
                            in (best.get("origin") or {}).items()}
        return self._home_seq

    def _set_home_locked(self, tenant: str, cell: str) -> None:
        self._homes[tenant] = cell
        self._home_seq += 1
        self._homes_dirty = True

    # ---------------------------------------------------------- routing

    def _forward(self, url: str, body: bytes,
                 headers: dict[str, str] | None = None
                 ) -> tuple[int, bytes]:
        """POST the raw request body to one cell's fleet router; same
        transport semantics as :meth:`..serving.router.Router._forward`
        — ``TimeoutError`` is never re-sendable, other ``OSError`` is
        failover-safe.  ``headers`` carries the X-DTF-* trace context
        down to the cell."""
        req = urllib.request.Request(
            url + "/generate", data=body,
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s + 10.0) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, TimeoutError) and not isinstance(
                    reason, ConnectionError):
                raise TimeoutError(str(reason)) from None
            if isinstance(reason, OSError):
                raise reason from None
            raise OSError(str(reason)) from None

    def route(self, body: bytes, tenant: str,
              wire: tuple[str | None, int, bool] | None = None
              ) -> tuple[int, bytes]:
        """Serve one caller request: throttle, choose a cell, forward,
        fail over.  One-response guarantee: transport failures and
        500s rotate to the next cell; 429s spill; 400 passes through;
        a forward timeout answers 503 and is NEVER re-sent; exhausting
        the cell set returns the last status seen or 503.

        ``wire`` is the inbound ``(trace, parent, forced)`` context —
        see :meth:`..serving.router.Router.route`; here the root span
        is ``route.global`` and each per-cell forward attempt a
        ``route.cell`` child."""
        token = False
        if self.throttle is not None:
            try:
                token = self.throttle.acquire(tenant)
            except QueueFull as e:
                with self._lock:
                    self._throttle_rejected += 1
                self._emit_cell("throttle_reject", tenant=tenant,
                                reason=str(e))
                self._trace_throttled(tenant, wire, str(e))
                return 429, json.dumps({"error": str(e)}).encode()
        try:
            return self._route_inner(body, tenant, wire)
        finally:
            if token:
                self.throttle.release(tenant)

    def _trace_throttled(self, tenant: str,
                         wire: tuple[str | None, int, bool] | None,
                         reason: str) -> None:
        """A throttle 429 never reaches ``_route_inner``, but it IS the
        interesting tail (blast-radius admission control fired) — record
        a zero-duration ``route.global`` span and the tier's keep
        verdict so the trace survives the sampler."""
        tracer = tracing.active()
        if tracer is None:
            return
        in_trace, in_parent, forced = wire or (None, 0, False)
        trace = in_trace or tracing.mint_trace("global")
        tracer.emit_span(
            "route.global", time.time(), 0.0, step=self._routed_total,
            parent_id=in_parent if in_trace else 0, trace=trace,
            tenant=tenant, cell="", failovers=0, rehomed="",
            status=429, error=reason[:200])
        if tracer.buffer is not None:
            tracer.buffer.retire(trace, tenant=tenant, status=429,
                                 forced=forced)

    def _route_inner(self, body: bytes, tenant: str,
                     wire: tuple[str | None, int, bool] | None = None
                     ) -> tuple[int, bytes]:
        t0 = time.perf_counter()
        t0_unix = time.time()
        tried: set[str] = set()
        failovers = 0
        last: tuple[int, bytes] | None = None
        served_by = ""
        rehomed_any = ""
        tracer = tracing.active()
        in_trace, in_parent, forced = wire or (None, 0, False)
        trace: str | None = None
        span_global = 0
        if tracer is not None:
            trace = in_trace or tracing.mint_trace("global")
            span_global = tracer.allocate_id()

        def finish(status: int) -> None:
            # The route.global root span + this tier's tail verdict.
            if tracer is None:
                return
            dur_ms = (time.perf_counter() - t0) * 1e3
            tracer.emit_span(
                "route.global", t0_unix, dur_ms,
                step=self._routed_total,
                parent_id=in_parent if in_trace else 0,
                span_id=span_global, trace=trace, tenant=tenant,
                cell=served_by, failovers=failovers,
                rehomed=rehomed_any, status=status, error="")
            if tracer.buffer is not None:
                tracer.buffer.retire(
                    trace, tenant=tenant, e2e_ms=dur_ms,
                    ok=status == 200, status=status,
                    failovers=failovers, forced=forced)

        while True:
            with self._lock:
                loads = {
                    name: cell_load(c.statz) + c.in_flight
                    for name, c in self._cells.items()
                    if c.state in ROUTABLE_CELL_STATES
                    and name not in tried}
                name, _spilled = choose_replica(
                    loads, tenant, self._homes, self.spill_margin)
                if name is None:
                    break
                if _spilled:
                    self._spill_total += 1
                home = self._homes.get(tenant)
                home_cell = self._cells.get(home) \
                    if home is not None else None
                home_routable = (home_cell is not None
                                 and home_cell.state
                                 in ROUTABLE_CELL_STATES)
                rehomed = ""
                if home is None:
                    self._set_home_locked(tenant, name)
                elif home != name and not home_routable \
                        and not _spilled:
                    # The home cell is dead/absent: this IS the
                    # failover re-home (a spill is a one-off and does
                    # not move the home).
                    self._origin.setdefault(tenant, home)
                    self._set_home_locked(tenant, name)
                    self._rehome_total += 1
                    rehomed = home
                c = self._cells[name]
                c.in_flight += 1
                c.routed += 1
                self._routed_total += 1
                poll_age_ms = (round((time.monotonic() - c.t_statz)
                                     * 1e3, 1)
                               if c.t_statz is not None else -1.0)
            if rehomed:
                rehomed_any = rehomed
                if self.throttle is not None:
                    self.throttle.mark_rehomed(tenant)
                self._emit_cell("tenant_rehome", cell=name,
                                tenant=tenant,
                                reason=f"home {rehomed} not routable")
            tried.add(name)
            ta_unix, ta = time.time(), time.perf_counter()
            headers = None
            span_attempt = 0
            if tracer is not None:
                span_attempt = tracer.allocate_id()
                # A retry proves the trace interesting — force every
                # downstream tier's tail sampler to keep its half.
                headers = tracing.wire_headers(
                    trace, span_attempt, sampled=forced or failovers > 0)

            def attempt_span(status: int, error: str = "") -> None:
                if tracer is None:
                    return
                tracer.emit_span(
                    "route.cell", ta_unix,
                    (time.perf_counter() - ta) * 1e3,
                    step=self._routed_total, parent_id=span_global,
                    span_id=span_attempt, trace=trace, tier="global",
                    cell=name, load=round(loads[name], 3),
                    spilled=_spilled, rehomed=rehomed,
                    poll_age_ms=poll_age_ms, status=status,
                    ok=status == 200, error=error[:200])

            try:
                status, payload = self._forward(c.url, body, headers)
            except TimeoutError:
                with self._lock:
                    c.in_flight -= 1
                    self._failed_total += 1
                attempt_span(504, "forward timeout")
                finish(504)
                return 503, json.dumps(
                    {"error": f"cell {name} timed out; "
                              "request may still be executing"}).encode()
            except OSError as e:
                with self._lock:
                    c.in_flight -= 1
                    c.fails += 1
                    dead = c.fails >= self.fail_after \
                        and c.state not in ("dead",)
                    if dead:
                        self._mark_cell_dead_locked(c, f"route: {e!r}")
                        rehome = self._rehome_tenants_locked(
                            c.name, reason=f"route {e!r}")
                    else:
                        rehome = []
                if dead:
                    self._emit_cell("cell_dead", cell=c.name,
                                    reason=f"route {e!r}")
                    self._emit_rehomes(rehome)
                attempt_span(0, repr(e))
                failovers += 1
                continue
            attempt_span(status)
            with self._lock:
                c.in_flight -= 1
                if status == 200:
                    c.fails = 0
                    c.served += 1
                    self._served_total += 1
                    if failovers:
                        self._failover_total += failovers
                    served_by = name
                    gap = self._gap_done_locked(tenant)
                else:
                    gap = None
            if gap is not None:
                self._emit_cell("failover_gap", cell=gap[0],
                                tenant=tenant, gap_ms=gap[1])
            if status in (500, 429):
                # 500: the fleet router already exhausted ITS members;
                # re-running the generate on another cell is safe.
                # 429: every member of that cell backpressured — spill
                # to the next cell, surface only when all cells are
                # full.
                last = (status, payload)
                failovers += status == 500
                continue
            finish(status)
            return status, payload
        if last is None:
            last = (503, json.dumps(
                {"error": "no cell available"}).encode())
        with self._lock:
            if last[0] != 429:
                self._failed_total += 1
        finish(last[0])
        return last

    def _gap_done_locked(self, tenant: str) -> tuple[str, float] | None:
        """Lock held.  First served request of a tenant displaced from
        a pending dead cell closes that cell's failover gap."""
        origin = self._origin.get(tenant)
        t_dead = self._gap_pending.pop(origin, None) \
            if origin is not None else None
        if t_dead is None:
            return None
        gap_ms = (time.time() - t_dead) * 1e3
        self._max_gap_ms = max(self._max_gap_ms, gap_ms)
        return origin, gap_ms

    def _rehome_tenants_locked(self, dead: str,
                               reason: str) -> list[tuple[str, str]]:
        """Lock held.  Move every tenant homed on ``dead`` to the
        least-loaded surviving cell NOW (waiting for each tenant's
        next request would stretch every failover gap by one arrival
        interval).  Returns ``(tenant, new_home)`` pairs for emission
        outside the lock."""
        loads = {name: cell_load(c.statz) + c.in_flight
                 for name, c in self._cells.items()
                 if c.state in ROUTABLE_CELL_STATES}
        moved: list[tuple[str, str]] = []
        for tenant in sorted(t for t, cell in self._homes.items()
                             if cell == dead):
            if not loads:
                # No survivor yet: drop the home; the next request
                # re-assigns (and still closes the gap).
                del self._homes[tenant]
                self._origin.setdefault(tenant, dead)
                self._home_seq += 1
                self._homes_dirty = True
                continue
            target, _ = choose_replica(loads, tenant, {}, 0.0)
            self._origin.setdefault(tenant, dead)
            self._set_home_locked(tenant, target)
            self._rehome_total += 1
            loads[target] = loads.get(target, 0.0) + 1.0
            moved.append((tenant, target))
        return moved

    def _emit_rehomes(self, moved: list[tuple[str, str]],
                      reason: str = "cell failover") -> None:
        for tenant, target in moved:
            if self.throttle is not None:
                self.throttle.mark_rehomed(tenant)
            self._emit_cell("tenant_rehome", cell=target, tenant=tenant,
                            reason=reason)

    # ------------------------------------------------------ health loop

    def _get_json(self, url: str, path: str,
                  timeout: float = 5.0) -> tuple[int, dict]:
        req = urllib.request.Request(url + path)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {}
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, OSError):
                raise reason from None
            raise OSError(str(reason)) from None

    @staticmethod
    def _fleet_burning(members: list[dict]) -> list[str]:
        return sorted({
            flag for m in members
            for flag in ((m.get("statz") or {}).get("slo") or {})
            .get("burning", ())})

    def poll_cells_once(self) -> None:
        """One health sweep (control thread; callable from tests).
        Probes every cell's ``/healthz`` + ``/fleetz`` CONCURRENTLY
        (a blackholed cell must not stall death detection for the
        rest), promotes/demotes, refreshes the statz snapshots routing
        reads, and drives burn-based re-home and the recovery policy."""
        with self._lock:
            targets = [(c.name, c.url) for c in self._cells.values()]
        probes: dict[str, tuple[int, dict, dict | None] | OSError] = {}

        def probe(name: str, url: str) -> None:
            try:
                code, health = self._get_json(url, "/healthz")
                fleetz = None
                if code == 200:
                    _, fleetz = self._get_json(url, "/fleetz")
                probes[name] = (code, health, fleetz)
            except OSError as e:
                probes[name] = e

        threads = [threading.Thread(target=probe, args=t, daemon=True)
                   for t in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events: list[tuple[str, dict]] = []
        rehomes: list[tuple[str, str]] = []
        for name, _url in targets:
            outcome = probes.get(name)
            with self._lock:
                c = self._cells.get(name)
                if c is None:
                    continue
                failing = isinstance(outcome, OSError) or (
                    outcome is not None and outcome[0] != 200)
                if outcome is None:
                    continue
                if failing:
                    reason = (repr(outcome)
                              if isinstance(outcome, OSError)
                              else f"healthz {outcome[0]}: "
                                   f"{outcome[1].get('status', '')}")
                    if c.state == "dead":
                        continue
                    c.fails += 1
                    if c.state == "starting":
                        if time.time() - c.t_added > self.boot_timeout_s:
                            self._mark_cell_dead_locked(
                                c, "boot timeout")
                            rehomes += self._rehome_tenants_locked(
                                name, reason="boot timeout")
                            events.append(("cell_dead", {
                                "cell": name,
                                "reason": "boot timeout"}))
                    elif c.fails >= self.fail_after:
                        self._mark_cell_dead_locked(c, reason)
                        rehomes += self._rehome_tenants_locked(
                            name, reason=reason)
                        events.append(("cell_dead", {
                            "cell": name, "reason": reason}))
                    continue
                _code, _health, fleetz = outcome
                c.fails = 0
                c.statz = (fleetz or {}).get("router") or {}
                c.t_statz = time.monotonic()
                c.members = (fleetz or {}).get("members") or []
                c.burning = self._fleet_burning(c.members)
                if c.burning:
                    if c.burn_since is None:
                        c.burn_since = time.monotonic()
                else:
                    c.burn_since = None
                    c.burn_rehomed = False
                if c.state == "starting":
                    c.state = "healthy"
                    events.append(("cell_up", {"cell": name,
                                               "reason": "adopted"}))
                elif c.state == "dead":
                    c.state = "healthy"
                    c.dead_reason = ""
                    self._gap_pending.pop(name, None)
                    events.append(("cell_up", {"cell": name,
                                               "reason": "recovered"}))
                    if self.rehome_policy == "return":
                        for tenant in sorted(
                                t for t, origin in self._origin.items()
                                if origin == name):
                            self._set_home_locked(tenant, name)
                            del self._origin[tenant]
                            self._return_total += 1
                            events.append(("tenant_return", {
                                "cell": name, "tenant": tenant,
                                "reason": "home cell recovered"}))
                # Sustained SLO burn: re-home the cell's tenants onto a
                # non-burning survivor without declaring it dead.
                if (self.burn_fail_s > 0 and c.state == "healthy"
                        and not c.burn_rehomed
                        and c.burn_since is not None
                        and time.monotonic() - c.burn_since
                        >= self.burn_fail_s):
                    others = [o for o in self._cells.values()
                              if o.name != name and o.state == "healthy"
                              and not o.burning]
                    if others:
                        c.burn_rehomed = True
                        loads = {o.name: cell_load(o.statz) + o.in_flight
                                 for o in others}
                        for tenant in sorted(
                                t for t, cell in self._homes.items()
                                if cell == name):
                            target, _ = choose_replica(loads, tenant,
                                                       {}, 0.0)
                            self._origin.setdefault(tenant, name)
                            self._set_home_locked(tenant, target)
                            self._rehome_total += 1
                            loads[target] += 1.0
                            rehomes.append((tenant, target))
                        events.append(("cell_burning", {
                            "cell": name,
                            "reason": f"slo burn {c.burning}"}))
        for action, fields in events:
            self._emit_cell(action, **fields)
        self._emit_rehomes(rehomes)

    def _control_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_cells_once()
                self.flush_homes()
                with self._lock:
                    self._ticks += 1
                now = time.monotonic()
                if now - self._last_cell_emit >= self.cell_emit_every_s:
                    self._last_cell_emit = now
                    self._emit_cell("poll")
            except Exception:  # noqa: BLE001 — the tier outlives a tick
                pass

    # -------------------------------------------------------- telemetry

    def _emit_cell(self, action: str, *, cell: str = "",
                   tenant: str = "", gap_ms: float = 0.0,
                   reason: str = "") -> None:
        """The ONE ``kind="cell"`` emit site — every field of
        ``REQUIRED_CELL_FIELDS`` is an explicit keyword here, so the
        dtflint telemetry-contract analyzer can prove the contract
        statically."""
        if self.telemetry is None:
            return
        with self._lock:
            cells = len(self._cells)
            healthy = sum(c.state == "healthy"
                          for c in self._cells.values())
            step = self._ticks
        self.telemetry.emit(
            "cell", step=step, action=action, cell=cell, tenant=tenant,
            gap_ms=round(float(gap_ms), 3), cells=cells,
            healthy_cells=healthy, reason=reason[:300])

    # ------------------------------------------------------------ views

    def stats(self) -> dict:
        """The global router's own ``/statz`` (role-tagged so a watcher
        knows it is neither a server's nor a fleet router's)."""
        with self._lock:
            cells = list(self._cells.values())
            out = {
                "role": "global_router",
                "cells": len(cells),
                "healthy_cells": sum(c.state == "healthy"
                                     for c in cells),
                "dead_cells": sum(c.state == "dead" for c in cells),
                "routed": self._routed_total,
                "served": self._served_total,
                "failed": self._failed_total,
                "failovers": self._failover_total,
                "spills": self._spill_total,
                "rehomes": self._rehome_total,
                "returns": self._return_total,
                "throttle_rejected": self._throttle_rejected,
                "max_failover_gap_ms": round(self._max_gap_ms, 3),
                "tenant_homes": dict(self._homes),
                "displaced": dict(self._origin),
                "home_seq": self._home_seq,
                "rehome_policy": self.rehome_policy,
                "queue_depth": sum(
                    (c.statz or {}).get("queue_depth") or 0
                    for c in cells if c.state == "healthy"),
                "active_slots": sum(
                    (c.statz or {}).get("active_slots") or 0
                    for c in cells if c.state == "healthy"),
            }
        if self.throttle is not None:
            out["throttle"] = self.throttle.snapshot()
        tracer = tracing.active()
        if tracer is not None and tracer.buffer is not None:
            out["serve_trace_sampled"] = tracer.buffer.stats()
        return out

    def cells_snapshot(self) -> dict:
        """The ``/cellz`` payload: global stats + per-cell views —
        ``tools/watch_serve.py --cells``'s one-poll feed."""
        with self._lock:
            cells = [c.view() for c in sorted(
                self._cells.values(), key=lambda c: c.name)]
        return {"global": self.stats(), "cells": cells}

    # -------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        assert self._http is not None, "start() first"
        return self._http.server_address[1]

    def start(self) -> None:
        self._http = ThreadingHTTPServer((self._host, self._port),
                                         self._make_handler())
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="global-router-http")
        self._http_thread.start()
        self._control = threading.Thread(
            target=self._control_loop, daemon=True,
            name="global-router-control")
        self._control.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        if self._control is not None:
            self._control.join(timeout=10.0)
        for client in self._kv_clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass
        self._kv_clients.clear()

    # ------------------------------------------------------------- HTTP

    def _make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet server
                pass

            def _reply_json(self, code: int, payload: dict) -> None:
                self._reply_raw(code, json.dumps(payload).encode())

            def _reply_raw(self, code: int, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    stats = router.stats()
                    if stats["healthy_cells"] == 0:
                        return self._reply_json(503, {
                            "status": "no_healthy_cell", **stats})
                    return self._reply_json(200, {"status": "ok",
                                                  **stats})
                if self.path == "/statz":
                    return self._reply_json(200, router.stats())
                if self.path == "/cellz":
                    return self._reply_json(200,
                                            router.cells_snapshot())
                return self._reply_json(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path != "/generate":
                    return self._reply_json(404,
                                            {"error": "unknown path"})
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) or b"{}"
                try:
                    tenant = str(json.loads(body).get(
                        "tenant", "default"))
                except (ValueError, AttributeError):
                    tenant = "default"
                status, payload = router.route(
                    body, tenant, wire=tracing.parse_wire(self.headers))
                return self._reply_raw(status, payload)

        return Handler
