"""Serving frontend: HTTP in, fair-scheduled continuous batching out.

:class:`ServingServer` glues the pieces of docs/serving.md together —
bounded per-tenant queues (:mod:`.scheduler`), the slot-batched engine
(:mod:`.engine`), and an engine loop thread that interleaves admission
with decode steps:

    handler threads ──submit──> FairScheduler ──pop──┐
                                                     v
                 engine loop:  [apply swap] [admit while slots+pages]
                               [decode one step] [complete retirees]

The loop admits every admissible request BEFORE each decode step, so a
request that arrives while other sequences are mid-decode joins the very
next step — continuous batching, per step, not per batch.  Responses
block their handler thread on the request's event (HTTP is the transport,
not the scheduler); a caller that times out marks its request abandoned
and the engine retires the lane at the next step boundary.

Wire format (JSON over HTTP/1.1, keep-alive):

- ``POST /generate``  ``{"prompt": [ids...], "num_tokens": N,
  "tenant": "name", "eos_id": id?, "temperature": t?, "top_k": k?,
  "top_p": p?, "seed": s?, "speculative": bool?}`` ->
  ``{"tokens": [prompt+generated...], "ttft_ms": ..., "tpot_ms": ...,
  "queue_ms": ..., "model_step": ...}`` (+ ``spec_rounds`` /
  ``spec_accepted_per_round`` when the speculative arm served it);
  400 malformed, 429 tenant queue full (back off), 503 timed out.
  ``speculative`` opts the request into the engine's paged speculative
  decode arm (greedy-only; honored when the server runs ``--spec_k``,
  plain decode otherwise — same tokens either way, see
  docs/speculative.md).
- ``GET /healthz`` -> engine identity + occupancy (+ the ``replica``
  identity block; status ``draining`` once a drain began).
- ``GET /statz``  -> the ``replica`` identity block (id, model
  namespace, uptime, engine generation), per-tenant scheduler stats,
  latency histogram snapshots (global + per tenant), KV-pool occupancy,
  SLO burn state (``tools/watch_serve.py``'s feed).
- ``GET /metricz`` -> Prometheus text exposition of every serve_*
  instrument, pool/queue occupancy, and SLO burn-rate gauges.
- ``POST /drain`` -> finish queued + in-flight work, 429 new
  submissions — the cooperative half of a fleet scale-down
  (``serving/router.py``).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import tracing
from ..utils.telemetry import split_instrument_label
from .engine import DecodeEngine, _ensure_request_trace
from .scheduler import FairScheduler, QueueFull, Request
from .slo import SloEngine


class ServingServer:
    """Own the engine loop + HTTP frontend; ``start()`` / ``shutdown()``."""

    def __init__(self, engine: DecodeEngine, scheduler: FairScheduler, *,
                 port: int = 8700, host: str = "127.0.0.1",
                 request_timeout_s: float = 120.0, telemetry=None,
                 slo: SloEngine | None = None,
                 slo_emit_every_s: float = 2.0,
                 meta: dict | None = None, replica_id: str = "",
                 trace_buffer=None):
        self.engine = engine
        self.scheduler = scheduler
        self.telemetry = telemetry
        self.slo = slo
        # Tail-sampling ring (serving/trace_buffer.py).  The caller arms
        # the same buffer onto the installed tracer; the server's job is
        # the retirement verdict (_complete / 429 reject) and surfacing
        # the kept/dropped counters on /statz.
        self.trace_buffer = trace_buffer
        self.slo_emit_every_s = float(slo_emit_every_s)
        self._last_slo_emit = 0.0
        self.request_timeout_s = float(request_timeout_s)
        self.meta = dict(meta or {})
        # Fleet identity (docs/serving.md, "Fleet"): which member of a
        # replicated tier this process is.  Standalone servers leave it
        # "" — the identity block still renders so a fleet of /statz
        # snapshots is never indistinguishable.
        self.replica_id = str(replica_id)
        self._t_start_unix = time.time()
        self._wake = threading.Condition()
        self._stop = False
        self._draining = False          # set by POST /drain (scale-down)
        self._dead: str | None = None   # set by _engine_fatal
        self._loop_thread: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._host, self._port = host, int(port)

    # -------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        assert self._http is not None, "start() first"
        return self._http.server_address[1]

    def start(self) -> None:
        self._http = ThreadingHTTPServer((self._host, self._port),
                                         self._make_handler())
        self._loop_thread = threading.Thread(
            target=self._engine_loop, daemon=True, name="serve-engine")
        self._loop_thread.start()
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="serve-http")
        self._http_thread.start()

    def shutdown(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)

    # ------------------------------------------------------ engine loop

    def _have_work(self) -> bool:
        return (self.engine.active_slots > 0
                or self.scheduler.depth() > 0)

    def _engine_loop(self) -> None:
        # Fatal-exception wrapper (docs/observability.md, "Flight
        # recorder"): the per-iteration handler below keeps the loop
        # alive through request-level failures, but anything that
        # escapes it — a BaseException, or the handler itself failing —
        # kills the serving thread.  Dump the telemetry ring first so a
        # crashed server leaves its last records, then fail the callers
        # so nobody blocks a full request_timeout_s on a dead loop.
        try:
            self._engine_loop_inner()
        except BaseException as e:  # noqa: BLE001 — dying, leave evidence
            self._engine_fatal(e)
            raise

    def _engine_fatal(self, exc: BaseException) -> None:
        msg = f"engine loop died: {type(exc).__name__}: {exc}"
        # Flag first: /healthz flips to 503 and new submissions fail
        # fast instead of queueing into a loop that will never pop them.
        self._dead = msg
        if self.telemetry is not None:
            # The record lands in the ring before the dump so the flight
            # file names its own cause of death.
            self.telemetry.emit("serve_fatal",
                                step=self.engine.step_index,
                                error=msg[:300])
            self.telemetry.dump_flight(reason=msg)
        try:
            for req in self.engine.fail_active(msg):
                self._complete(req)
            # Queued requests were never served: release their callers
            # WITHOUT running them through the admitted/completed books
            # (a /statz scrape of the dead-but-listening server must not
            # report them as served).
            for req in self.scheduler.drain():
                req.error = msg
                req.event.set()
        except Exception:  # noqa: BLE001 — best-effort caller release
            pass

    def _engine_loop_inner(self) -> None:
        engine, sched = self.engine, self.scheduler
        while True:
            with self._wake:
                # Idle wait with a timeout, dropping the lock each tick
                # so housekeeping (swap adoption, SLO emission — file
                # I/O) never runs under the condition submit() handlers
                # need to grab.
                if not self._stop and not self._have_work():
                    self._wake.wait(timeout=0.5)
                stop = self._stop
            if stop:
                self._slo_tick(force=True)
                break
            engine.apply_pending_swap()
            self._slo_tick()
            if engine.active_slots == 0 and sched.depth() == 0:
                continue    # still idle — back to the timed wait
            admitting = None
            try:
                # Admit everything admissible RIGHT NOW (slots + pages),
                # fair-ordered; then one decode step for the whole batch.
                while engine.free_slots > 0:
                    admitting = sched.next_request(engine.can_admit)
                    if admitting is None:
                        break
                    self._trace_queue(admitting)
                    engine.admit(admitting)
                    admitting = None
                for req in engine.step(queue_depth=sched.depth()):
                    self._complete(req)
            except Exception as e:  # noqa: BLE001 — fail loud, stay up
                msg = f"{type(e).__name__}: {e}"
                if admitting is not None:
                    # admit() raised after the pop: pages are freed and
                    # the lane was never seated, so the request is in
                    # neither the queue nor a slot — complete it here or
                    # its caller blocks the full request_timeout_s.
                    admitting.error = msg
                    self._complete(admitting)
                for req in self.engine.fail_active(msg):
                    self._complete(req)

    def _trace_queue(self, req: Request) -> None:
        """Emit the request's ``serve.queue`` span at pop time: submit ->
        scheduler release, with the tenant and the residual queue depth —
        the span that tells queueing latency apart from prefill."""
        tracer = tracing.active()
        if tracer is None:
            return
        _ensure_request_trace(tracer, req)
        dur_ms = (time.perf_counter() - req.t_submit) * 1e3
        tracer.emit_span(
            "serve.queue", req.t_submit_unix, dur_ms,
            step=self.engine.step_index, parent_id=req.span_root,
            trace=req.trace, request_id=req.id, tenant=req.tenant,
            queue_depth=self.scheduler.depth())

    def _slo_tick(self, force: bool = False) -> None:
        """Periodic SLO evaluation -> ``kind="slo"`` + ``serve_tenant``
        telemetry records and burn gauges (engine-loop thread only)."""
        if self.slo is None and self.telemetry is None:
            return
        now = time.monotonic()
        if not force and now - self._last_slo_emit < self.slo_emit_every_s:
            return
        self._last_slo_emit = now
        tel = self.telemetry
        step = self.engine.step_index
        if self.slo is not None and tel is not None:
            # Stream records only — /metricz gets the properly labelled
            # serve_slo_burn_rate{tenant,objective,window} series from
            # SloEngine.prometheus_lines (the bracket convention on
            # instrument names is tenant-only).
            for entry in self.slo.evaluate():
                tel.emit("slo", step=step, **entry)
        if tel is not None:
            tel.gauge("serve_queue_depth_hwm").set(
                self.scheduler.depth_hwm())
            for tenant, st in self.scheduler.stats().items():
                tel.emit("serve_tenant", step=step, tenant=tenant,
                         queued=st["queued"], queued_hwm=st["queued_hwm"],
                         rejected=st["rejected"],
                         abandoned=st["abandoned"],
                         completed=st["completed"],
                         served_tokens=st["served_tokens"])
                tel.gauge(f"serve_queued_hwm[{tenant}]").set(
                    st["queued_hwm"])

    def _complete(self, req: Request) -> None:
        self.scheduler.account(req.tenant, len(req.tokens))
        self.scheduler.complete(req.tenant)
        if req.abandoned:
            self.scheduler.note_abandoned(req.tenant)
        ok = req.error is None and not req.abandoned
        if self.slo is not None:
            self.slo.observe_request(
                req.tenant, ttft_ms=req.ttft_ms, tpot_ms=req.tpot_ms,
                e2e_ms=req.e2e_ms, ok=ok)
        # Retirement IS the tail-sampling decision point: every span this
        # request parked (engine tree included — the root serve.request
        # span was parked during engine retirement, just before this
        # call) is flushed or dropped wholesale, now that the verdict
        # (latency, error, upstream force flag) actually exists.
        if self.trace_buffer is not None and req.trace is not None:
            self.trace_buffer.retire(
                req.trace, tenant=req.tenant, e2e_ms=req.e2e_ms,
                ok=ok, status=200 if ok else 500,
                forced=req.trace_forced)
        req.event.set()

    def adopt_wire_trace(self, request: Request, headers) -> None:
        """Adopt inbound ``X-DTF-*`` trace context (utils/tracing.py):
        the request's spans join the CALLER'S trace — the engine's
        ``serve.request`` root nests under the routing tier's span
        instead of starting a fresh tree.  ``_ensure_request_trace``
        honors the pre-set ``span_root``/``trace``, so every downstream
        span site is untouched."""
        tracer = tracing.active()
        if tracer is None:
            return
        trace, parent, forced = tracing.parse_wire(headers)
        if trace is None:
            return
        request.trace = trace
        request.wire_parent = parent
        request.trace_forced = forced
        request.span_root = tracer.allocate_id()

    def retire_rejected(self, request: Request, status: int) -> None:
        """Tail-sampling verdict for a request rejected BEFORE admission
        (429 backpressure): it never reaches ``_complete``, but the
        sampler still records the decision — a throttled request is
        exactly the interesting tail the buffer exists to keep."""
        if self.trace_buffer is not None and request.trace is not None:
            self.trace_buffer.retire(
                request.trace, tenant=request.tenant, status=int(status),
                forced=request.trace_forced)

    # ---------------------------------------------------------- submit

    def submit(self, request: Request) -> Request:
        """Queue + block until done; raises on error/backpressure."""
        if self._dead:
            # The engine loop is gone — nothing will ever pop the queue.
            # Fail fast (500) instead of parking the caller for the full
            # request_timeout_s on a dead server.
            raise RuntimeError(self._dead)
        if self._draining:
            # Scale-down drain: in-flight and queued work finishes, new
            # work backpressures (429) so a fleet router routes it to a
            # sibling replica instead.
            raise QueueFull(
                f"replica {self.replica_id or '?'} is draining; "
                "route elsewhere")
        self.engine.validate(request)      # 400s before queueing
        try:
            self.scheduler.submit(request)  # may raise QueueFull (429)
        except QueueFull:
            if self.telemetry is not None:
                self.telemetry.counter("serve_rejected").inc()
                self.telemetry.counter(
                    f"serve_rejected[{request.tenant}]").inc()
            if self.slo is not None:
                self.slo.observe_admission(request.tenant, rejected=True)
            raise
        if self.slo is not None:
            self.slo.observe_admission(request.tenant, rejected=False)
        with self._wake:
            self._wake.notify_all()
        if not request.event.wait(self.request_timeout_s):
            request.abandoned = True
            if self.telemetry is not None:
                self.telemetry.counter("serve_timeouts").inc()
            raise TimeoutError(
                f"request waited past {self.request_timeout_s:.0f}s "
                "(server overloaded)")
        if request.error:
            raise RuntimeError(request.error)
        return request

    def request_swap(self, params, step: int) -> None:
        """Stage a hot swap and wake the loop (the watcher's swap_fn)."""
        self.engine.swap_params(params, step)
        with self._wake:
            self._wake.notify_all()

    def begin_drain(self) -> dict:
        """Flip the replica into drain mode (``POST /drain``): queued and
        in-flight requests finish, new submissions 429 so the router
        spills them to siblings.  Returns the drain progress snapshot the
        router polls to decide when the replica is empty."""
        with self._wake:
            self._draining = True
            self._wake.notify_all()
        return {"status": "draining",
                "active": self.engine.active_slots,
                "queued": self.scheduler.depth()}

    # ------------------------------------------------------------ stats

    def replica_info(self) -> dict:
        """Identity block carried on ``/statz`` and ``/healthz`` so a
        fleet of snapshots is attributable: replica id, the model
        namespace served, process uptime, and the engine generation
        (hot-swap count — two replicas on different generations are
        serving different weights)."""
        return {
            "id": self.replica_id,
            "model": self.meta.get("model"),
            "uptime_s": round(time.time() - self._t_start_unix, 1),
            "engine_generation": self.engine.swaps,
            "model_step": self.engine.model_step,
            "draining": self._draining,
        }

    def stats(self) -> dict:
        out = {
            "replica": self.replica_info(),
            "engine": self.engine.stats(),
            "tenants": self.scheduler.stats(),
            "queue_depth": self.scheduler.depth(),
            "queue_depth_hwm": self.scheduler.depth_hwm(),
        }
        if self.telemetry is not None:
            snap = self.telemetry.summary()
            out["latency"] = {
                name: snap["histograms"].get(name, {"count": 0})
                for name in ("serve_ttft_ms", "serve_tpot_ms",
                             "serve_e2e_ms", "serve_step_ms")}
            # Per-tenant distributions: bracketed instrument names
            # ("serve_ttft_ms[search]") fan out into a tenant-keyed map
            # for the watch_serve table.
            per_tenant: dict = {}
            for key, hist in snap["histograms"].items():
                base, tenant = split_instrument_label(key)
                if tenant is not None and base in (
                        "serve_ttft_ms", "serve_tpot_ms", "serve_e2e_ms"):
                    per_tenant.setdefault(tenant, {})[base] = hist
            if per_tenant:
                out["tenant_latency"] = per_tenant
            out["counters"] = {
                k: v for k, v in snap["counters"].items()
                if k.startswith("serve_")}
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.trace_buffer is not None:
            out["serve_trace_sampled"] = self.trace_buffer.stats()
        return out

    def metricz_text(self) -> str:
        """Prometheus text exposition (``GET /metricz``): every serve_*
        instrument on the bus, live pool/queue occupancy, and the SLO
        burn gauges — one scrape target per serving process."""
        lines = ["# dtf serving metrics (docs/observability.md, "
                 "'Serving tracing & SLOs')"]
        if self.telemetry is not None:
            lines.extend(self.telemetry.prometheus_lines(prefix="serve_"))
        pool = self.engine.allocator.snapshot()
        lines.extend([
            "# TYPE serve_kv_pool_pages gauge",
            f'serve_kv_pool_pages{{state="in_use"}} '
            f'{pool["pages_in_use"]}',
            f'serve_kv_pool_pages{{state="free"}} {pool["free_pages"]}',
            f'serve_kv_pool_pages{{state="peak"}} {pool["peak_in_use"]}',
            "# TYPE serve_kv_pool_fragmentation gauge",
            f'serve_kv_pool_fragmentation '
            f'{pool["internal_fragmentation"]}',
            "# TYPE serve_queue_depth gauge",
            f"serve_queue_depth {self.scheduler.depth()}",
            "# TYPE serve_model_step gauge",
            f"serve_model_step {self.engine.model_step}",
        ])
        if self.slo is not None:
            lines.extend(self.slo.prometheus_lines())
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------- HTTP

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet server
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if server._dead:
                        # The frontend outlives a dead engine loop —
                        # load balancers must stop routing here.
                        return self._reply(503, {
                            "status": "engine_dead",
                            "error": server._dead,
                            "replica": server.replica_info(),
                            **server.meta})
                    return self._reply(200, {
                        "status": ("draining" if server._draining
                                   else "ok"),
                        "replica": server.replica_info(),
                        **server.meta,
                        **server.engine.stats()})
                if self.path == "/statz":
                    return self._reply(200, server.stats())
                if self.path == "/metricz":
                    body = server.metricz_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return None
                return self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path == "/drain":
                    return self._reply(200, server.begin_drain())
                if self.path != "/generate":
                    return self._reply(404, {"error": "unknown path"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    request = Request(
                        body["prompt"], int(body.get("num_tokens", 16)),
                        tenant=str(body.get("tenant", "default")),
                        eos_id=(int(body["eos_id"])
                                if body.get("eos_id") is not None
                                else None),
                        temperature=float(body.get("temperature", 0.0)),
                        top_k=int(body.get("top_k", 0)),
                        top_p=float(body.get("top_p", 0.0)),
                        seed=int(body.get("seed", 0)),
                        speculative=bool(body.get("speculative", False)))
                except (KeyError, TypeError, ValueError):
                    return self._reply(400, {"error": "malformed request"})
                server.adopt_wire_trace(request, self.headers)
                try:
                    server.submit(request)
                except QueueFull as e:
                    server.retire_rejected(request, 429)
                    return self._reply(429, {"error": str(e)})
                except TimeoutError as e:
                    return self._reply(503, {"error": str(e)})
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                except RuntimeError as e:
                    return self._reply(500, {"error": str(e)})
                payload = {
                    "tokens": request.prompt + request.tokens,
                    "tokens_out": len(request.tokens),
                    "queue_ms": request.queue_ms,
                    "ttft_ms": request.ttft_ms,
                    "tpot_ms": request.tpot_ms,
                    "model_step": server.engine.model_step,
                }
                if request.speculative and request.spec_rounds:
                    payload["spec_rounds"] = request.spec_rounds
                    payload["spec_accepted_per_round"] = round(
                        len(request.tokens) / request.spec_rounds, 2)
                return self._reply(200, payload)

        return Handler
