"""Serving frontend: HTTP in, fair-scheduled continuous batching out.

:class:`ServingServer` glues the pieces of docs/serving.md together —
bounded per-tenant queues (:mod:`.scheduler`), the slot-batched engine
(:mod:`.engine`), and an engine loop thread that interleaves admission
with decode steps:

    handler threads ──submit──> FairScheduler ──pop──┐
                                                     v
                 engine loop:  [apply swap] [admit while slots+pages]
                               [decode one step] [complete retirees]

The loop admits every admissible request BEFORE each decode step, so a
request that arrives while other sequences are mid-decode joins the very
next step — continuous batching, per step, not per batch.  Responses
block their handler thread on the request's event (HTTP is the transport,
not the scheduler); a caller that times out marks its request abandoned
and the engine retires the lane at the next step boundary.

Wire format (JSON over HTTP/1.1, keep-alive):

- ``POST /generate``  ``{"prompt": [ids...], "num_tokens": N,
  "tenant": "name", "eos_id": id?, "temperature": t?, "top_k": k?,
  "top_p": p?, "seed": s?, "speculative": bool?}`` ->
  ``{"tokens": [prompt+generated...], "ttft_ms": ..., "tpot_ms": ...,
  "queue_ms": ..., "model_step": ...}`` (+ ``spec_rounds`` /
  ``spec_accepted_per_round`` when the speculative arm served it);
  400 malformed, 429 tenant queue full (back off), 503 timed out.
  ``speculative`` opts the request into the engine's paged speculative
  decode arm (greedy-only; honored when the server runs ``--spec_k``,
  plain decode otherwise — same tokens either way, see
  docs/speculative.md).
- ``GET /healthz`` -> engine identity + occupancy.
- ``GET /statz``  -> per-tenant scheduler stats, latency histogram
  snapshots, KV-pool occupancy (the ``--watch`` table's feed).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .engine import DecodeEngine
from .scheduler import FairScheduler, QueueFull, Request


class ServingServer:
    """Own the engine loop + HTTP frontend; ``start()`` / ``shutdown()``."""

    def __init__(self, engine: DecodeEngine, scheduler: FairScheduler, *,
                 port: int = 8700, host: str = "127.0.0.1",
                 request_timeout_s: float = 120.0, telemetry=None,
                 meta: dict | None = None):
        self.engine = engine
        self.scheduler = scheduler
        self.telemetry = telemetry
        self.request_timeout_s = float(request_timeout_s)
        self.meta = dict(meta or {})
        self._wake = threading.Condition()
        self._stop = False
        self._loop_thread: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._host, self._port = host, int(port)

    # -------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        assert self._http is not None, "start() first"
        return self._http.server_address[1]

    def start(self) -> None:
        self._http = ThreadingHTTPServer((self._host, self._port),
                                         self._make_handler())
        self._loop_thread = threading.Thread(
            target=self._engine_loop, daemon=True, name="serve-engine")
        self._loop_thread.start()
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="serve-http")
        self._http_thread.start()

    def shutdown(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)

    # ------------------------------------------------------ engine loop

    def _have_work(self) -> bool:
        return (self.engine.active_slots > 0
                or self.scheduler.depth() > 0)

    def _engine_loop(self) -> None:
        engine, sched = self.engine, self.scheduler
        while True:
            with self._wake:
                while not self._stop and not self._have_work():
                    # Idle wait with a timeout so a staged hot swap is
                    # adopted promptly even on a quiet server.
                    self._wake.wait(timeout=0.5)
                    engine.apply_pending_swap()
                if self._stop:
                    break
            admitting = None
            try:
                # Admit everything admissible RIGHT NOW (slots + pages),
                # fair-ordered; then one decode step for the whole batch.
                while engine.free_slots > 0:
                    admitting = sched.next_request(engine.can_admit)
                    if admitting is None:
                        break
                    engine.admit(admitting)
                    admitting = None
                for req in engine.step(queue_depth=sched.depth()):
                    self._complete(req)
            except Exception as e:  # noqa: BLE001 — fail loud, stay up
                msg = f"{type(e).__name__}: {e}"
                if admitting is not None:
                    # admit() raised after the pop: pages are freed and
                    # the lane was never seated, so the request is in
                    # neither the queue nor a slot — complete it here or
                    # its caller blocks the full request_timeout_s.
                    admitting.error = msg
                    self._complete(admitting)
                for req in self.engine.fail_active(msg):
                    self._complete(req)

    def _complete(self, req: Request) -> None:
        self.scheduler.account(req.tenant, len(req.tokens))
        self.scheduler.complete(req.tenant)
        req.event.set()

    # ---------------------------------------------------------- submit

    def submit(self, request: Request) -> Request:
        """Queue + block until done; raises on error/backpressure."""
        self.engine.validate(request)      # 400s before queueing
        self.scheduler.submit(request)     # may raise QueueFull (429)
        with self._wake:
            self._wake.notify_all()
        if not request.event.wait(self.request_timeout_s):
            request.abandoned = True
            if self.telemetry is not None:
                self.telemetry.counter("serve_timeouts").inc()
            raise TimeoutError(
                f"request waited past {self.request_timeout_s:.0f}s "
                "(server overloaded)")
        if request.error:
            raise RuntimeError(request.error)
        return request

    def request_swap(self, params, step: int) -> None:
        """Stage a hot swap and wake the loop (the watcher's swap_fn)."""
        self.engine.swap_params(params, step)
        with self._wake:
            self._wake.notify_all()

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        out = {
            "engine": self.engine.stats(),
            "tenants": self.scheduler.stats(),
            "queue_depth": self.scheduler.depth(),
        }
        if self.telemetry is not None:
            snap = self.telemetry.summary()
            out["latency"] = {
                name: snap["histograms"].get(name, {"count": 0})
                for name in ("serve_ttft_ms", "serve_tpot_ms",
                             "serve_step_ms")}
            out["counters"] = {
                k: v for k, v in snap["counters"].items()
                if k.startswith("serve_")}
        return out

    # ------------------------------------------------------------- HTTP

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet server
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._reply(200, {
                        "status": "ok", **server.meta,
                        **server.engine.stats()})
                if self.path == "/statz":
                    return self._reply(200, server.stats())
                return self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path != "/generate":
                    return self._reply(404, {"error": "unknown path"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    request = Request(
                        body["prompt"], int(body.get("num_tokens", 16)),
                        tenant=str(body.get("tenant", "default")),
                        eos_id=(int(body["eos_id"])
                                if body.get("eos_id") is not None
                                else None),
                        temperature=float(body.get("temperature", 0.0)),
                        top_k=int(body.get("top_k", 0)),
                        top_p=float(body.get("top_p", 0.0)),
                        seed=int(body.get("seed", 0)),
                        speculative=bool(body.get("speculative", False)))
                except (KeyError, TypeError, ValueError):
                    return self._reply(400, {"error": "malformed request"})
                try:
                    server.submit(request)
                except QueueFull as e:
                    return self._reply(429, {"error": str(e)})
                except TimeoutError as e:
                    return self._reply(503, {"error": str(e)})
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                except RuntimeError as e:
                    return self._reply(500, {"error": str(e)})
                payload = {
                    "tokens": request.prompt + request.tokens,
                    "tokens_out": len(request.tokens),
                    "queue_ms": request.queue_ms,
                    "ttft_ms": request.ttft_ms,
                    "tpot_ms": request.tpot_ms,
                    "model_step": server.engine.model_step,
                }
                if request.speculative and request.spec_rounds:
                    payload["spec_rounds"] = request.spec_rounds
                    payload["spec_accepted_per_round"] = round(
                        len(request.tokens) / request.spec_rounds, 2)
                return self._reply(200, payload)

        return Handler
