"""Tail-based trace sampling — keep the interesting traces, decide AFTER
retirement (docs/observability.md, "Cross-tier tracing & tail sampling").

Head sampling throws the dice when a request arrives, which is exactly
when nothing is known about it: the 1-in-10 000 request that failed over
across cells is dropped with probability 0.9999.  Tail sampling inverts
the order — every span a request produces parks in a bounded in-memory
ring keyed by trace id, and only at retirement, when the verdict (slow?
errored? failed over? throttled?) is in hand, does the whole trace get
flushed to the telemetry stream or dropped wholesale.

Two pieces:

- :class:`TailSampler` — the pure decision function.  Keep iff the
  request was slow (per-tenant latency threshold taken from the SLO
  objectives), errored, failed over, 429'd, force-kept by an upstream
  tier (``X-DTF-Sampled``), or head-sampled at ``--trace_sample_rate``
  (a deterministic trace-id hash, so every tier reaches the SAME verdict
  without coordination).  Injecting ``clock`` keeps tests deterministic.
- :class:`TraceBuffer` — the bounded per-tier ring.  ``park`` is what
  :meth:`utils.tracing.Tracer.emit_span` calls for request-keyed spans
  when a buffer is armed; ``retire`` applies the sampler and either
  flushes or drops.  Overflow degrades to head-sampling on the evicted
  (oldest) trace and never blocks the engine loop; kept/dropped/overflow
  counters surface on ``/statz`` and as per-decision ``trace_sample``
  records (the ``serve_trace_sampled`` gauge).

Zero-cost when off: without an installed tracer no span exists to park,
and without an armed buffer ``emit_span`` writes straight to telemetry
exactly as before this module existed.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Iterable

from ..utils import tracing

#: Retirement statuses the sampler treats as backpressure / error.
_BACKPRESSURE_STATUS = 429


def slow_thresholds(objectives: Iterable[Any]) -> dict[str, float]:
    """Per-tenant "slow" thresholds (ms) from parsed SLO objectives
    (:func:`serving.slo.parse_slos`).  A request is slow when its e2e
    latency exceeds the tenant's tightest ``e2e`` objective threshold;
    tenants without one inherit the ``"*"`` objective.  Non-latency and
    non-e2e objectives (ttft/tpot target different request phases) are
    ignored rather than misapplied to e2e."""
    out: dict[str, float] = {}
    for obj in objectives or ():
        if getattr(obj, "metric", None) != "e2e_ms":
            continue
        if obj.threshold_ms is None:
            continue
        prev = out.get(obj.tenant)
        if prev is None or obj.threshold_ms < prev:
            out[obj.tenant] = float(obj.threshold_ms)
    return out


class TailSampler:
    """Pure keep/drop decision for a retired trace.

    ``decide`` consults only its arguments (plus the construction-time
    thresholds and rate) — no I/O, no globals — so tests drive it with
    synthetic verdicts and an injected clock.  ``clock`` is only used to
    timestamp decisions on the record the buffer emits.
    """

    def __init__(self, sample_rate: float = 0.0,
                 slow_ms: dict[str, float] | None = None,
                 clock=time.time):
        self.sample_rate = float(sample_rate)
        self.slow_ms = dict(slow_ms or {})
        self.clock = clock

    def slow_threshold(self, tenant: str | None) -> float | None:
        if tenant is not None and tenant in self.slow_ms:
            return self.slow_ms[tenant]
        return self.slow_ms.get("*")

    def decide(self, trace_id: str, *, tenant: str | None = None,
               e2e_ms: float | None = None, ok: bool = True,
               status: int = 200, failovers: int = 0,
               forced: bool = False) -> tuple[bool, str]:
        """``(keep, reason)`` — reasons, in precedence order: ``forced``
        (upstream tier demanded it), ``error``, ``backpressure`` (429),
        ``failover``, ``slow``, ``head`` (the deterministic hash), else
        ``drop``."""
        if forced:
            return True, "forced"
        if not ok or int(status) >= 500:
            return True, "error"
        if int(status) == _BACKPRESSURE_STATUS:
            return True, "backpressure"
        if int(failovers) > 0:
            return True, "failover"
        threshold = self.slow_threshold(tenant)
        if (threshold is not None and e2e_ms is not None
                and float(e2e_ms) > threshold):
            return True, "slow"
        if tracing.head_sampled(trace_id, self.sample_rate):
            return True, "head"
        return False, "drop"


class TraceBuffer:
    """Bounded per-tier ring of in-flight request spans, keyed by trace.

    One buffer per process (tier); armed onto the tracer via
    ``tracer.buffer = buffer``.  All operations are short critical
    sections over a dict — ``park`` never blocks on I/O, so the engine
    loop's span emission stays hot-path safe.  ``capacity`` bounds the
    number of DISTINCT in-flight traces; when exceeded the oldest parked
    trace is evicted early with a head-sampling verdict (degraded mode:
    the tail verdict for that trace is lost, the stream records the
    overflow).
    """

    def __init__(self, telemetry, sampler: TailSampler, *,
                 tier: str = "engine", capacity: int = 256,
                 clock=time.time):
        self._telemetry = telemetry
        self.sampler = sampler
        self.tier = str(tier)
        self.capacity = int(capacity)
        self.clock = clock
        self._lock = threading.Lock()
        self._parked: "collections.OrderedDict[str, list[dict]]" = (
            collections.OrderedDict())
        self.kept = 0
        self.dropped = 0
        self.overflow = 0

    # ---------------------------------------------------------- parking

    def park(self, trace_id: str, fields: dict) -> None:
        """Hold one span record until its trace retires.  Called by
        ``Tracer.emit_span`` for request-keyed spans."""
        evicted: tuple[str, list[dict]] | None = None
        with self._lock:
            bucket = self._parked.get(trace_id)
            if bucket is None:
                if len(self._parked) >= self.capacity:
                    evicted = self._parked.popitem(last=False)
                    self.overflow += 1
                bucket = self._parked[trace_id] = []
            bucket.append(fields)
        if evicted is not None:
            # Degraded mode: the evicted trace can no longer wait for its
            # tail verdict — fall back to the deterministic head-sampling
            # coin so SOME overflow traces still surface.
            ev_trace, ev_spans = evicted
            keep = tracing.head_sampled(ev_trace, self.sampler.sample_rate)
            self._settle(ev_trace, ev_spans, keep,
                         "overflow_head" if keep else "overflow")

    def retire(self, trace_id: str, *, tenant: str | None = None,
               e2e_ms: float | None = None, ok: bool = True,
               status: int = 200, failovers: int = 0,
               forced: bool = False) -> bool:
        """Apply the tail verdict to a finished trace: flush every parked
        span (keep) or drop them wholesale.  Returns the keep decision so
        the caller can propagate it (e.g. onto a response header)."""
        with self._lock:
            spans = self._parked.pop(trace_id, [])
        keep, reason = self.sampler.decide(
            trace_id, tenant=tenant, e2e_ms=e2e_ms, ok=ok, status=status,
            failovers=failovers, forced=forced)
        self._settle(trace_id, spans, keep, reason, tenant=tenant)
        return keep

    def _settle(self, trace_id: str, spans: list[dict], keep: bool,
                reason: str, tenant: str | None = None) -> None:
        with self._lock:
            if keep:
                self.kept += 1
            else:
                self.dropped += 1
            kept, dropped = self.kept, self.dropped
        if keep:
            for fields in spans:
                self._telemetry.emit("span", **fields)
        # ONE trace_sample emit site — the serve_trace_sampled gauge.
        # Every decision is recorded (kept AND dropped) so the stream
        # proves what the sampler did; the running counters ride along.
        self._telemetry.emit(
            "trace_sample", step=0,
            trace_id=str(trace_id),
            tier=self.tier,
            sampled=int(bool(keep)),
            reason=str(reason),
            tenant=str(tenant) if tenant is not None else "",
            kept=kept,
            dropped=dropped,
            overflow=self.overflow,
            t_unix=round(float(self.clock()), 6))

    # ------------------------------------------------------------ statz

    def stats(self) -> dict:
        """Counters for ``/statz`` (the ``serve_trace_sampled`` gauge)."""
        with self._lock:
            return {
                "tier": self.tier,
                "kept": self.kept,
                "dropped": self.dropped,
                "overflow": self.overflow,
                "parked": len(self._parked),
            }
