"""Per-tenant serving SLOs — sliding windows, error budgets, burn-rate
alerts (docs/observability.md, "Serving tracing & SLOs").

An *objective* states what fraction of a tenant's requests must be good:
``search:ttft_p95_ms<=50`` reads "95% of tenant ``search``'s requests
reach their first token within 50 ms".  The complement of the target
(here 5%) is the **error budget**; the **burn rate** is how fast the
live bad-event fraction is consuming it (``bad_fraction / budget`` — 1.0
means the budget is spent exactly at the allowed rate, 20 means the
tenant will exhaust a month's budget in ~36 hours).

Alerting follows the multi-window burn-rate recipe (Google SRE workbook
§5): an objective is ``burning`` only when BOTH a short and a long
sliding window exceed the burn threshold — the short window makes the
alert fast to clear when the problem stops, the long window keeps a
brief blip from paging.  Windows are wall-clock deques of (time, bad)
events in constant-ish memory (trimmed to the long window every
observation).

Objectives cover:

- latency percentiles — ``ttft``/``tpot``/``e2e`` against a millisecond
  threshold at a percentile target (``ttft_p95_ms<=50``); a request that
  errored counts bad, a request that legitimately lacks the figure (tpot
  on a 1-token generation) is skipped;
- ``error_rate<=X`` — engine-failed / timed-out requests over completions;
- ``reject_rate<=X`` — HTTP 429 backpressure rejections over submissions
  (the queue-bound budget).

The engine is transport-agnostic and clock-injectable (tests drive
``now`` explicitly); :class:`..serving.server.ServingServer` feeds it and
periodically emits ``kind="slo"`` telemetry records that
``tools/summarize_run.py`` rolls into the report and
``tools/watch_serve.py`` renders live.
"""

from __future__ import annotations

import collections
import dataclasses
import re
import threading
import time
from typing import Any

#: Latency metrics an objective can target (value source on the request).
LATENCY_METRICS = ("ttft_ms", "tpot_ms", "e2e_ms")
RATE_METRICS = ("error_rate", "reject_rate")

_PCT_RE = re.compile(r"^(ttft|tpot|e2e)_p(\d{2,3})_ms<=([0-9.]+)$")
_RATE_RE = re.compile(r"^(error_rate|reject_rate)<=([0-9.]+)$")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One tenant's promise: ``target`` fraction of events good.

    ``tenant`` may be ``"*"`` (applies to every tenant, evaluated over
    the merged event stream).  For latency metrics ``threshold_ms``
    defines good; for rate metrics goodness is the event itself (ok
    completion / accepted submission) and ``target = 1 - max_rate``.
    """

    tenant: str
    metric: str               # ttft_ms | tpot_ms | e2e_ms | error_rate | ...
    target: float             # good-event fraction promised, in (0, 1)
    threshold_ms: float | None = None

    def __post_init__(self):
        if self.metric not in LATENCY_METRICS + RATE_METRICS:
            raise ValueError(f"unknown SLO metric {self.metric!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), "
                             f"got {self.target}")
        if (self.metric in LATENCY_METRICS) != (self.threshold_ms
                                                is not None):
            raise ValueError("latency objectives need threshold_ms; "
                             "rate objectives must not set it")

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction (the error budget)."""
        return 1.0 - self.target

    @property
    def label(self) -> str:
        """The spec-string form, e.g. ``ttft_p95_ms<=50``."""
        if self.metric in LATENCY_METRICS:
            pct = f"{self.target * 100:g}".replace(".", "")
            return (f"{self.metric[:-3]}_p{pct}_ms"
                    f"<={self.threshold_ms:g}")
        return f"{self.metric}<={self.budget:g}"


def parse_slos(spec: str) -> list[Objective]:
    """``"tenant:objective,..."`` -> objectives (the ``--slo`` CLI flag).

    Objective grammar: ``{ttft|tpot|e2e}_p{50..999}_ms<=<ms>`` (p999 =
    99.9%) or ``{error_rate|reject_rate}<=<fraction>``.  Tenant ``*``
    applies to all tenants::

        --slo "search:ttft_p95_ms<=50,search:error_rate<=0.01,
               *:e2e_p99_ms<=2000"
    """
    out: list[Objective] = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        tenant, sep, obj = part.partition(":")
        if not sep or not tenant or not obj:
            raise ValueError(f"bad SLO spec {part!r}; "
                             "want tenant:objective<=value")
        m = _PCT_RE.match(obj)
        if m:
            stem, pct, threshold = m.groups()
            # Three digits means per-mille and ONLY p999 (99.9%) — p100,
            # p500 etc. are typos that would otherwise silently parse to
            # nonsense targets (p100 -> "10% of requests fast").
            if len(pct) == 3 and pct != "999":
                raise ValueError(
                    f"bad SLO percentile p{pct} in {obj!r}; two digits "
                    "(p50..p99) or p999 (= 99.9%)")
            target = int(pct) / (1000.0 if len(pct) == 3 else 100.0)
            out.append(Objective(tenant, f"{stem}_ms", target,
                                 threshold_ms=float(threshold)))
            continue
        m = _RATE_RE.match(obj)
        if m:
            metric, rate = m.groups()
            out.append(Objective(tenant, metric, 1.0 - float(rate)))
            continue
        raise ValueError(
            f"bad SLO objective {obj!r}; want e.g. ttft_p95_ms<=50, "
            "tpot_p99_ms<=20, e2e_p50_ms<=500, error_rate<=0.01, "
            "reject_rate<=0.05")
    return out


class SloEngine:
    """Sliding-window SLO evaluation + burn-rate alerting.

    Thread-safe: the engine loop observes completions, HTTP handler
    threads observe rejections, and ``/statz``/``/metricz`` handlers
    evaluate concurrently.  ``clock`` is injectable for tests (defaults
    to ``time.monotonic``).
    """

    def __init__(self, objectives: list[Objective] | None = None, *,
                 short_window_s: float = 60.0,
                 long_window_s: float = 600.0,
                 burn_threshold: float = 14.4,
                 clock=time.monotonic):
        if long_window_s < short_window_s:
            raise ValueError("long_window_s must be >= short_window_s")
        self.objectives = list(objectives or ())
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        #: Both windows must burn at or above this multiple of the budget
        #: rate to alert — 14.4 is the classic fast-burn page threshold
        #: (a 30-day budget gone in ~2 days).
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        # Per-objective event windows: deque[(t, bad)] trimmed to the
        # long window; plus per-tenant completion times for live QPS.
        self._events: list[collections.deque] = [
            collections.deque() for _ in self.objectives]
        self._done: dict[str, collections.deque] = {}
        self._ever_burning: set[str] = set()

    # ------------------------------------------------------ observation

    def _matching(self, tenant: str):
        for i, obj in enumerate(self.objectives):
            if obj.tenant == "*" or obj.tenant == tenant:
                yield i, obj

    def _push(self, idx: int, bad: bool, now: float) -> None:
        q = self._events[idx]
        q.append((now, bool(bad)))
        horizon = now - self.long_window_s
        while q and q[0][0] < horizon:
            q.popleft()

    def observe_request(self, tenant: str, *, ttft_ms: float | None,
                        tpot_ms: float | None, e2e_ms: float | None,
                        ok: bool = True, now: float | None = None) -> None:
        """Fold one finished request into every matching window."""
        now = self._clock() if now is None else float(now)
        values = {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms, "e2e_ms": e2e_ms}
        with self._lock:
            dq = self._done.setdefault(tenant, collections.deque())
            dq.append(now)
            horizon = now - self.long_window_s
            while dq and dq[0] < horizon:
                dq.popleft()
            for i, obj in self._matching(tenant):
                if obj.metric == "error_rate":
                    self._push(i, not ok, now)
                elif obj.metric in LATENCY_METRICS:
                    value = values[obj.metric]
                    if not ok:
                        self._push(i, True, now)
                    elif value is not None:
                        self._push(i, value > obj.threshold_ms, now)
                    # ok but no figure (tpot on a 1-token reply): skip —
                    # the event carries no evidence either way.

    def observe_admission(self, tenant: str, rejected: bool,
                          now: float | None = None) -> None:
        """Fold one submission (accepted or 429-rejected) into the
        reject-rate windows."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            for i, obj in self._matching(tenant):
                if obj.metric == "reject_rate":
                    self._push(i, rejected, now)

    # ------------------------------------------------------- evaluation

    @staticmethod
    def _window_counts(q, horizon: float) -> tuple[int, int]:
        good = bad = 0
        for t, is_bad in reversed(q):
            if t < horizon:
                break
            if is_bad:
                bad += 1
            else:
                good += 1
        return good, bad

    def evaluate(self, now: float | None = None) -> list[dict[str, Any]]:
        """Per-objective window state + burn rates (JSON-ready; the
        ``kind="slo"`` record payloads)."""
        now = self._clock() if now is None else float(now)
        out: list[dict[str, Any]] = []
        with self._lock:
            for i, obj in enumerate(self.objectives):
                q = self._events[i]
                g_s, b_s = self._window_counts(q, now - self.short_window_s)
                g_l, b_l = self._window_counts(q, now - self.long_window_s)

                def burn(good: int, bad: int) -> float:
                    total = good + bad
                    if not total:
                        return 0.0
                    return (bad / total) / obj.budget

                burn_s, burn_l = burn(g_s, b_s), burn(g_l, b_l)
                # Burn is capped at 1/budget (100% of events bad), so a
                # generous budget (> 1/threshold, e.g. a p50 objective)
                # could never reach the global threshold — alert such
                # objectives at full budget burn instead of never.
                alert_at = min(self.burn_threshold, 1.0 / obj.budget)
                burning = ((g_s + b_s) > 0
                           and burn_s >= alert_at
                           and burn_l >= alert_at)
                if burning:
                    self._ever_burning.add(f"{obj.tenant}:{obj.label}")
                entry: dict[str, Any] = {
                    "tenant": obj.tenant,
                    "objective": obj.label,
                    "metric": obj.metric,
                    "target": obj.target,
                    "budget": round(obj.budget, 6),
                    "good_short": g_s, "bad_short": b_s,
                    "good_long": g_l, "bad_long": b_l,
                    "burn_short": round(burn_s, 3),
                    "burn_long": round(burn_l, 3),
                    "burn_alert_at": round(alert_at, 3),
                    "burning": burning,
                    "window_short_s": self.short_window_s,
                    "window_long_s": self.long_window_s,
                }
                if obj.threshold_ms is not None:
                    entry["threshold_ms"] = obj.threshold_ms
                out.append(entry)
        return out

    def tenant_qps(self, now: float | None = None) -> dict[str, float]:
        """Completions per second over the short window, per tenant."""
        now = self._clock() if now is None else float(now)
        horizon = now - self.short_window_s
        with self._lock:
            return {
                tenant: round(sum(1 for t in dq if t >= horizon)
                              / self.short_window_s, 3)
                for tenant, dq in sorted(self._done.items())
            }

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        """The ``/statz``-embedded view ``watch_serve`` renders."""
        evals = self.evaluate(now)
        with self._lock:
            ever = sorted(self._ever_burning)
        return {
            "objectives": evals,
            "burning": [f"{e['tenant']}:{e['objective']}"
                        for e in evals if e["burning"]],
            "ever_burning": ever,
            "burn_threshold": self.burn_threshold,
            "window_short_s": self.short_window_s,
            "window_long_s": self.long_window_s,
            "tenant_qps": self.tenant_qps(now),
        }

    def prometheus_lines(self, now: float | None = None) -> list[str]:
        """The objectives as ``/metricz`` samples."""
        from ..utils.telemetry import _prom_escape, _prom_num
        lines = [
            "# TYPE serve_slo_burn_rate gauge",
            "# TYPE serve_slo_burning gauge",
            "# TYPE serve_slo_bad_events gauge",
        ]
        for e in self.evaluate(now):
            labels = (f'tenant="{_prom_escape(e["tenant"])}",'
                      f'objective="{_prom_escape(e["objective"])}"')
            lines.append(f'serve_slo_burn_rate{{{labels},window="short"}} '
                         f'{_prom_num(e["burn_short"])}')
            lines.append(f'serve_slo_burn_rate{{{labels},window="long"}} '
                         f'{_prom_num(e["burn_long"])}')
            lines.append(f'serve_slo_burning{{{labels}}} '
                         f'{1 if e["burning"] else 0}')
            lines.append(f'serve_slo_bad_events{{{labels}}} '
                         f'{e["bad_long"]}')
        return lines
