"""Hot model swap — watch the checkpoint/anchor plane, verify, stage.

A serving process must pick up the training job's newer checkpoints
without restarting (and without dropping in-flight streams — the engine
side of that contract lives in :meth:`..serving.engine.DecodeEngine.
swap_params`).  The watcher here is the detection/loading half:

- **Discovery** — poll ``<logdir>/checkpoints`` for a step newer than the
  one being served (``tools/checkpoint_io.list_step_dirs``).  When a
  coordination client is supplied, the chief's init-done key
  (``dtf/initialized`` — republished at every durable save by
  ``training/supervisor.py``) is consulted first as a cheap "newest step"
  hint, so the common no-news poll is one KV round trip, not a directory
  walk.
- **Integrity** — a candidate is loaded only when
  ``tools/checkpoint_io.verify_checkpoint`` accepts it (``valid``, or
  ``unverified`` for legacy saves); a half-written or corrupt save is
  skipped this poll and retried when its manifest lands — the serving
  tier must never swap garbage into the hot path.
- **Staging** — the raw tree is restored and handed to ``swap_fn`` OFF
  the engine thread; the engine adopts it between steps.

The watcher is a daemon thread; failures are recorded as telemetry
(``kind="recovery"``, action ``swap_poll_error`` / ``swap_load_error``)
and retried next poll — a broken checkpoint plane degrades serving to
stale weights, never to a crash.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

from ..tools.checkpoint_io import list_step_dirs, verify_checkpoint
from ..training.supervisor import INIT_DONE_KEY
from ..utils import tracing


def newest_verified_step(ckpt_dir: str, min_step: int = -1
                         ) -> tuple[int, str] | None:
    """Newest step under ``ckpt_dir`` (> ``min_step``) whose directory
    passes integrity verification; skips corrupt candidates downward."""
    for step, step_dir in reversed(list_step_dirs(ckpt_dir)):
        if step <= min_step:
            return None
        status, _ = verify_checkpoint(step_dir)
        if status in ("valid", "unverified"):
            return step, step_dir
    return None


class ModelWatcher:
    """Poll-verify-load-stage loop feeding the engine's hot swap.

    ``load_fn(step) -> params`` restores the serving tree for a step (the
    CLI wires ``tools/serve.load_gpt_params``); ``swap_fn(params, step)``
    stages it (``DecodeEngine.swap_params`` behind the server's wakeup).
    """

    def __init__(self, logdir: str,
                 load_fn: Callable[[int], object],
                 swap_fn: Callable[[object, int], None], *,
                 initial_step: int = 0, poll_s: float = 2.0,
                 coord_client=None, telemetry=None):
        self._ckpt_dir = os.path.join(logdir, "checkpoints")
        self._load_fn = load_fn
        self._swap_fn = swap_fn
        self.current_step = int(initial_step)
        self._poll_s = float(poll_s)
        self._coord = coord_client
        self._telemetry = telemetry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ polls

    def _hint_step(self) -> int | None:
        """The coordination plane's newest-durable-step hint, if any."""
        if self._coord is None:
            return None
        try:
            value = self._coord.kv_get(INIT_DONE_KEY)
            return int(value) if value is not None else None
        except Exception:
            return None  # the directory poll below is the ground truth

    def poll_once(self) -> int | None:
        """One discovery+verify+load+stage cycle; returns the step swapped
        in, or None when there was nothing newer (or the candidate failed
        verification/loading — retried next poll)."""
        hint = self._hint_step()
        if hint is not None and hint <= self.current_step:
            return None  # cheap no-news exit: nothing newer is durable
        try:
            found = newest_verified_step(self._ckpt_dir, self.current_step)
        except OSError as e:
            self._record("swap_poll_error", repr(e))
            return None
        if found is None:
            return None
        step, _ = found
        t0 = time.perf_counter()
        try:
            # The off-engine-thread half of a swap (restore + prepare) —
            # traced so a trace shows WHY the engine later paused.
            with tracing.span("serve.swap_load", step=step,
                              to_model_step=step):
                params = self._load_fn(step)
        except Exception as e:  # noqa: BLE001 — stale weights, not a crash
            self._record("swap_load_error", f"step {step}: {e!r}")
            return None
        self._swap_fn(params, step)
        self.current_step = step
        if self._telemetry is not None:
            self._telemetry.emit(
                "recovery", step=step, action="swap_staged",
                load_ms=round((time.perf_counter() - t0) * 1e3, 1))
        return step

    def _record(self, action: str, detail: str) -> None:
        if self._telemetry is not None:
            self._telemetry.emit("recovery", step=self.current_step,
                                 action=action, detail=detail[:300])

    # ---------------------------------------------------------- thread

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self._poll_s):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001
                    self._record("swap_poll_error", repr(e))

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="model-watcher")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "ModelWatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()
