"""Fleet frontend — N serving replicas behind one statz-routed,
SLO-autoscaled HTTP endpoint (docs/serving.md, "Fleet").

A single :class:`..serving.server.ServingServer` is both the capacity
ceiling and the availability ceiling of the serving tier.  The router
turns N of them into one endpoint that speaks the SAME wire format a
single server does (``POST /generate`` / ``GET /healthz`` / ``/statz``),
so every existing :class:`..serving.client.ServeClient` caller works
unchanged — TF-Replicator's single-program-multi-role pattern: the same
engine binary plays replica or (through ``tools/serve_fleet.py``)
frontend, by role.

Three loops, three jobs:

- **Routing** (handler threads) — each admission goes to the replica
  with the lowest live load: queue depth + slot occupancy + KV-pool
  occupancy from the member's last ``/statz`` snapshot, plus the
  router's own in-flight count toward that member (the snapshot is a
  poll old; in-flight is the router's real-time correction).  Tenants
  are **affine**: a tenant sticks to the replica that has been serving
  it (decode-state locality, and the fairness books stay in one place)
  until that replica's load exceeds the best alternative by
  ``spill_margin`` — then the request *spills* to the least-loaded
  member.  Failures fail over: a connection refused/reset or HTTP 500
  marks the attempt failed and the SAME request is re-routed to the
  next-best member — the caller sees one response, never a socket
  error.  429 (tenant queue full / draining) spills the same way and
  only surfaces when EVERY member backpressures.
- **Health** (control thread) — each member's ``/healthz`` + ``/statz``
  are polled every ``poll_s``.  A member reporting ``engine_dead``
  (the PR-8 engine-fatal → 503 path) or failing ``fail_after``
  consecutive probes is marked dead and drained: its tenants re-home on
  the next route, its in-flight forwards fail over, and — with
  ``respawn`` — a replacement is spawned from the checkpoint plane via
  ``spawn_fn`` and adopted once its own ``/healthz`` turns ok.
- **Autoscaling** (control thread) — the replicas' SLO engines already
  compute per-tenant burn rate (``serving/slo.py``); the router closes
  the loop.  :class:`AutoscalePolicy` scales UP when any tenant has
  been burning for ``burn_sustain_s`` (a blip shorter than that — or a
  flapping objective — never scales), DOWN when the whole fleet has
  been idle for ``idle_sustain_s``, with a shared ``cooldown_s`` so
  consecutive actions cannot oscillate.  Scale-down is graceful: the
  victim is ``POST /drain``-ed (new work 429s to siblings), removed
  from routing, and reaped only once empty.

Telemetry: one ``kind="route"`` record per caller request (which
replica served it, how many failovers it survived, wall latency) and
``kind="fleet"`` snapshots/events (membership, health, autoscale
actions) — ``tools/summarize_run.py`` rolls both into a fleet section
and ``--check`` enforces their field contracts
(``REQUIRED_ROUTE_FIELDS`` / ``REQUIRED_FLEET_FIELDS``).

The policy pieces (:func:`replica_load`, :func:`choose_replica`,
:class:`AutoscalePolicy`) are pure and clock-injectable — unit-tested
without sockets in tests/test_router.py.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
import urllib.error
import urllib.request

from ..utils import tracing

#: Member lifecycle: spawned/adopted -> starting -(healthz ok)-> healthy
#: -(drain begun)-> draining -(empty, reaped)-> stopped;
#: healthy/starting/draining -(engine_dead or fail_after probes)-> dead.
REPLICA_STATES = ("starting", "healthy", "draining", "dead", "stopped")

#: States a new request may be routed to.
ROUTABLE_STATES = ("healthy",)


# ------------------------------------------------------- routing policy


def replica_load(statz: dict | None) -> float:
    """One replica's load figure from its ``/statz`` snapshot.

    Queue depth dominates — each queued request weighs as much as a
    replica's ENTIRE possible occupancy pressure (slot + KV fractions
    sum to at most 2), because queued work is waiting *now*; slot
    occupancy and KV-pool occupancy break ties among empty-queue
    replicas toward the one with free decode lanes and free pages.  A
    member with no snapshot yet scores 0 (a freshly adopted replica
    should attract load)."""
    if not statz:
        return 0.0
    eng = statz.get("engine") or {}
    pool = eng.get("kv_pool") or {}
    slots = eng.get("num_slots") or 1
    active = (eng.get("active_slots") or 0) / max(1, slots)
    kv = pool.get("utilization") or 0.0
    queue = statz.get("queue_depth") or 0
    return 2.0 * float(queue) + float(active) + float(kv)


def choose_replica(loads: dict[str, float], tenant: str,
                   affinity: dict[str, str],
                   spill_margin: float = 2.0) -> tuple[str | None, bool]:
    """Pick a member for ``tenant`` given each candidate's live load.

    Returns ``(replica_id, spilled)``.  The tenant's affine replica wins
    while its load stays within ``spill_margin`` of the best candidate;
    beyond that the request spills to the least-loaded member
    (``spilled=True``).  A dead/absent affine replica is simply
    re-homed, not a spill.  Ties break on replica id so the choice is
    deterministic for tests."""
    if not loads:
        return None, False
    best = min(loads, key=lambda rid: (loads[rid], rid))
    home = affinity.get(tenant)
    if home is not None and home in loads:
        if loads[home] <= loads[best] + spill_margin:
            return home, False
        return best, True
    return best, False


# ----------------------------------------------------------- autoscale


class AutoscalePolicy:
    """Hysteresis for the scale decision — pure, clock-injectable.

    ``observe()`` is fed the current fleet view each control tick and
    returns ``"up"``, ``"down"``, or ``None``.  Burn must SUSTAIN for
    ``burn_sustain_s`` before an up (one burning evaluation — or an
    objective flapping in and out of burn — never scales), idle must
    sustain ``idle_sustain_s`` before a down, and any action starts a
    shared ``cooldown_s`` window during which the policy stays quiet.
    Not thread-safe by itself: the router calls it from the single
    control thread."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 burn_sustain_s: float = 6.0,
                 idle_sustain_s: float = 60.0,
                 cooldown_s: float = 30.0,
                 clock=time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.burn_sustain_s = float(burn_sustain_s)
        self.idle_sustain_s = float(idle_sustain_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._burn_since: float | None = None
        self._idle_since: float | None = None
        self._last_action_t: float | None = None
        self.last_action: str | None = None

    def _cooled(self, now: float) -> bool:
        return (self._last_action_t is None
                or now - self._last_action_t >= self.cooldown_s)

    def observe(self, *, replicas: int, burning: bool, idle: bool,
                now: float | None = None) -> str | None:
        """One control tick: ``replicas`` counts live members (starting
        included — a booting replica is capacity already paid for),
        ``burning`` is "any tenant's SLO is burning fleet-wide",
        ``idle`` is "no queued, active, or in-flight work anywhere"."""
        now = self._clock() if now is None else float(now)
        if burning:
            self._idle_since = None
            if self._burn_since is None:
                self._burn_since = now
            if (now - self._burn_since >= self.burn_sustain_s
                    and replicas < self.max_replicas
                    and self._cooled(now)):
                # Re-arm: a burn that persists must re-sustain past the
                # cooldown before the NEXT step up.
                self._burn_since = None
                self._last_action_t = now
                self.last_action = "up"
                return "up"
            return None
        self._burn_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
            if (now - self._idle_since >= self.idle_sustain_s
                    and replicas > self.min_replicas
                    and self._cooled(now)):
                self._idle_since = None
                self._last_action_t = now
                self.last_action = "down"
                return "down"
        else:
            self._idle_since = None
        return None

    def snapshot(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "burn_sustain_s": self.burn_sustain_s,
            "idle_sustain_s": self.idle_sustain_s,
            "cooldown_s": self.cooldown_s,
            "last_action": self.last_action,
        }


# ------------------------------------------------------------- members


class ReplicaHandle:
    """One fleet member's book: identity, health, and serving credit.

    All mutation happens under the router's lock.  ``served`` counts
    only requests this replica actually answered — a request re-routed
    off a dying member is credited to the member that completed it, so
    a dead replica's books freeze at what it truly served."""

    def __init__(self, replica_id: str, url: str, handle: Any = None,
                 state: str = "starting"):
        assert state in REPLICA_STATES, state
        self.id = replica_id
        self.url = url.rstrip("/")
        self.handle = handle          # opaque (e.g. subprocess.Popen)
        self.state = state
        self.statz: dict | None = None
        self.fails = 0                # consecutive probe/route failures
        self.in_flight = 0            # router-side outstanding forwards
        self.routed = 0               # forwards attempted
        self.served = 0               # 200s actually answered
        self.failovers_absorbed = 0   # requests rescued FROM siblings
        self.dead_reason: str | None = None
        self.replaced = False         # a respawn already covers this death
        self.reaped = False           # reap_fn already ran on the handle
        self.t_added = time.time()
        self.t_statz: float | None = None   # monotonic, last statz refresh

    def view(self) -> dict:
        """The /fleetz member entry (snapshot under the router lock)."""
        eng = (self.statz or {}).get("engine") or {}
        return {
            "id": self.id,
            "url": self.url,
            "state": self.state,
            "load": round(replica_load(self.statz), 3),
            "in_flight": self.in_flight,
            "routed": self.routed,
            "served": self.served,
            "failovers_absorbed": self.failovers_absorbed,
            "dead_reason": self.dead_reason,
            "engine_step": eng.get("engine_step"),
            "model_step": eng.get("model_step"),
            "active_slots": eng.get("active_slots"),
            "num_slots": eng.get("num_slots"),
            "queue_depth": (self.statz or {}).get("queue_depth"),
            "replica": (self.statz or {}).get("replica"),
            "statz": self.statz,
        }


# --------------------------------------------------------------- router


class Router:
    """The fleet frontend.  ``add_replica()`` members, ``start()``,
    ``shutdown()``.  See the module docstring for the three loops."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 telemetry=None, poll_s: float = 1.0,
                 spill_margin: float = 2.0, fail_after: int = 2,
                 request_timeout_s: float = 120.0,
                 autoscale: AutoscalePolicy | None = None,
                 spawn_fn: Callable[[], tuple[str, str, Any]]
                 | None = None,
                 reap_fn: Callable[[ReplicaHandle], None] | None = None,
                 respawn: bool = False,
                 fleet_emit_every_s: float = 2.0,
                 boot_timeout_s: float = 600.0):
        self.telemetry = telemetry
        self.poll_s = float(poll_s)
        self.spill_margin = float(spill_margin)
        self.fail_after = int(fail_after)
        self.request_timeout_s = float(request_timeout_s)
        self.autoscale = autoscale
        self.spawn_fn = spawn_fn
        self.reap_fn = reap_fn
        self.respawn = bool(respawn)
        self.fleet_emit_every_s = float(fleet_emit_every_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self._lock = threading.Lock()
        self._members: dict[str, ReplicaHandle] = {}
        self._affinity: dict[str, str] = {}     # tenant -> replica id
        self._next_auto_id = 0
        self._respawns = 0
        self._routed_total = 0
        self._served_total = 0
        self._failed_total = 0
        self._failover_total = 0
        self._spill_total = 0
        self._max_failover_ms = 0.0
        self._ticks = 0
        self._last_fleet_emit = 0.0
        self._stop = threading.Event()
        self._control: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._host, self._port = host, int(port)

    # ------------------------------------------------------- membership

    def add_replica(self, url: str, *, handle: Any = None,
                    replica_id: str | None = None,
                    state: str = "starting") -> str:
        """Adopt a member by URL (spawned here or anywhere else).  New
        members start in ``starting`` and attract traffic once a health
        probe promotes them; tests may pass ``state="healthy"``.

        Auto-assigned ids use the ``a<N>`` namespace (``a0, a1, ...``)
        and skip taken names, so adopted-by-URL members can never
        collide with a spawner's own ``r<N>`` numbering."""
        with self._lock:
            if replica_id is None:
                while f"a{self._next_auto_id}" in self._members:
                    self._next_auto_id += 1
                replica_id = f"a{self._next_auto_id}"
                self._next_auto_id += 1
            if replica_id in self._members:
                raise ValueError(f"duplicate replica id {replica_id!r}")
            self._members[replica_id] = ReplicaHandle(
                replica_id, url, handle=handle, state=state)
        return replica_id

    def _mark_dead_locked(self, m: ReplicaHandle, reason: str) -> None:
        """Lock held.  Kill the member's routing eligibility and re-home
        its tenants; its in-flight forwards fail over on their own."""
        m.state = "dead"
        m.dead_reason = reason[:300]
        for tenant in [t for t, rid in self._affinity.items()
                       if rid == m.id]:
            del self._affinity[tenant]

    # ---------------------------------------------------------- routing

    def _forward(self, url: str, body: bytes,
                 headers: dict[str, str] | None = None
                 ) -> tuple[int, bytes]:
        """POST the raw request body to one replica; returns
        ``(status, body)`` for pass-through statuses, raises
        ``TimeoutError`` on a forward timeout (the replica may STILL be
        executing the request — never re-sendable) and other
        ``OSError``/``ConnectionError`` on transport death (nothing was
        served — safe to fail over).  ``headers`` carries the X-DTF-*
        trace context to the replica."""
        req = urllib.request.Request(
            url + "/generate", data=body,
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s + 10.0) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, TimeoutError) and not isinstance(
                    reason, ConnectionError):
                raise TimeoutError(str(reason)) from None
            if isinstance(reason, OSError):
                raise reason from None
            raise OSError(str(reason)) from None

    def route(self, body: bytes, tenant: str,
              wire: tuple[str | None, int, bool] | None = None
              ) -> tuple[int, bytes]:
        """Serve one caller request: choose, forward, fail over.

        Returns the final ``(status, body)``.  Transport failures and
        500s rotate to the next member; 429s spill; 400 passes through
        untried elsewhere (it is the request's fault, deterministically).
        Exhausting the member set returns the last replica status seen,
        or 503 when nothing was reachable at all.

        ``wire`` is the inbound ``(trace, parent, forced)`` context from
        :func:`utils.tracing.parse_wire`.  With a tracer installed the
        whole route becomes one ``route.fleet`` span (adopting the
        caller's trace, or minting one when this router IS the top
        tier), each forward attempt a ``route.attempt`` child carrying
        the member, its load score, the spill/affinity decision, the
        statz-poll staleness, and — on failure — the dead replica's id
        and the retry latency.  The chosen attempt's span id rides the
        X-DTF-Parent header so the replica's ``serve.request`` tree
        nests under it."""
        t0 = time.perf_counter()
        t0_unix = time.time()
        tried: set[str] = set()
        failovers = 0
        spilled_any = False
        last: tuple[int, bytes] | None = None
        served_by = ""
        tracer = tracing.active()
        in_trace, in_parent, forced = wire or (None, 0, False)
        trace: str | None = None
        span_fleet = 0
        if tracer is not None:
            trace = in_trace or tracing.mint_trace("fleet")
            span_fleet = tracer.allocate_id()

        def finish(status: int) -> None:
            # The route.fleet root span + this tier's tail verdict, at
            # the single point the outcome is known.
            if tracer is None:
                return
            dur_ms = (time.perf_counter() - t0) * 1e3
            tracer.emit_span(
                "route.fleet", t0_unix, dur_ms, step=self._routed_total,
                parent_id=in_parent if in_trace else 0,
                span_id=span_fleet, trace=trace, tenant=tenant,
                replica=served_by, failovers=failovers,
                spilled=spilled_any, status=status)
            if tracer.buffer is not None:
                tracer.buffer.retire(
                    trace, tenant=tenant, e2e_ms=dur_ms,
                    ok=status == 200, status=status,
                    failovers=failovers, forced=forced)

        while True:
            with self._lock:
                loads = {
                    rid: replica_load(m.statz) + m.in_flight
                    for rid, m in self._members.items()
                    if m.state in ROUTABLE_STATES and rid not in tried}
                rid, spilled = choose_replica(
                    loads, tenant, self._affinity, self.spill_margin)
                if rid is None:
                    break
                m = self._members[rid]
                m.in_flight += 1
                m.routed += 1
                self._routed_total += 1
                if spilled:
                    self._spill_total += 1
                    spilled_any = True
                elif tenant not in self._affinity:
                    self._affinity[tenant] = rid
                poll_age_ms = (round((time.monotonic() - m.t_statz) * 1e3,
                                     1)
                               if m.t_statz is not None else -1.0)
            tried.add(rid)
            ta_unix, ta = time.time(), time.perf_counter()
            headers = None
            span_attempt = 0
            if tracer is not None:
                span_attempt = tracer.allocate_id()
                # A retry already proves the trace interesting — force
                # the downstream tier's tail sampler to keep its half
                # (it retires before this tier's own verdict exists).
                headers = tracing.wire_headers(
                    trace, span_attempt, sampled=forced or failovers > 0)

            def attempt_span(status: int, error: str = "") -> None:
                if tracer is None:
                    return
                tracer.emit_span(
                    "route.attempt", ta_unix,
                    (time.perf_counter() - ta) * 1e3,
                    step=self._routed_total, parent_id=span_fleet,
                    span_id=span_attempt, trace=trace, tier="fleet",
                    replica=rid, load=round(loads[rid], 3),
                    spilled=spilled, poll_age_ms=poll_age_ms,
                    status=status, ok=status == 200, error=error[:200])

            try:
                status, payload = self._forward(m.url, body, headers)
            except TimeoutError:
                # The replica may still be executing this request —
                # re-sending it elsewhere would double-execute, and a
                # slow-but-alive member must not be counted toward
                # fail_after (the health poll owns that verdict) — the
                # same carve-out ServeClient makes for its own retries.
                with self._lock:
                    m.in_flight -= 1
                    self._failed_total += 1
                attempt_span(504, "forward timeout")
                self._emit_route(tenant, "", failovers, spilled_any, t0,
                                 504)
                finish(504)
                return 503, json.dumps(
                    {"error": f"replica {rid} timed out; "
                              "request may still be executing"}).encode()
            except OSError as e:
                with self._lock:
                    m.in_flight -= 1
                    m.fails += 1
                    dead = m.fails >= self.fail_after \
                        and m.state not in ("dead", "stopped")
                    if dead:
                        self._mark_dead_locked(m, f"route: {e!r}")
                if dead:
                    self._emit_fleet("replica_dead",
                                     reason=f"{m.id}: route {e!r}")
                attempt_span(0, repr(e))
                failovers += 1
                continue
            attempt_span(status)
            with self._lock:
                m.in_flight -= 1
                if status == 200:
                    m.fails = 0
                    m.served += 1
                    self._served_total += 1
                    if failovers:
                        m.failovers_absorbed += 1
                        self._failover_total += failovers
                        self._max_failover_ms = max(
                            self._max_failover_ms,
                            (time.perf_counter() - t0) * 1e3)
                    served_by = rid
            if status == 500:
                # Engine-loop death answers 500 ("engine loop died") —
                # and a generate is safely re-runnable — so a 500 rotates
                # like a transport failure; the health poll decides
                # whether the member is actually dead.
                last = (status, payload)
                failovers += 1
                continue
            if status == 429:
                # Backpressure/draining: spill to the next member; only
                # an all-members-full fleet surfaces the 429.  Counted
                # only when selection didn't already count this attempt
                # as an affinity spill (no double-booking one hop).
                last = (status, payload)
                spilled_any = True
                if not spilled:
                    with self._lock:
                        self._spill_total += 1
                continue
            self._emit_route(tenant, served_by, failovers, spilled_any,
                             t0, status)
            finish(status)
            return status, payload
        if last is None:
            last = (503, json.dumps(
                {"error": "no replica available"}).encode())
        with self._lock:
            if last[0] != 429:
                self._failed_total += 1
        self._emit_route(tenant, "", failovers, spilled_any, t0, last[0])
        finish(last[0])
        return last

    def _emit_route(self, tenant: str, replica: str, failovers: int,
                    spilled: bool, t0: float, status: int) -> None:
        if self.telemetry is None:
            return
        self.telemetry.emit(
            "route", step=self._routed_total, tenant=tenant,
            replica=replica, failovers=failovers, spilled=spilled,
            route_ms=round((time.perf_counter() - t0) * 1e3, 3),
            ok=status == 200, status=status)

    # ------------------------------------------------------ health loop

    def _get_json(self, url: str, path: str,
                  timeout: float = 5.0) -> tuple[int, dict]:
        req = urllib.request.Request(url + path)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {}
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, OSError):
                raise reason from None
            raise OSError(str(reason)) from None

    def poll_members_once(self) -> None:
        """One health/statz sweep (control thread; callable from tests).
        Promotes starting members whose /healthz turned ok, demotes
        members that report engine_dead or stop answering, refreshes the
        /statz snapshots routing reads, and reaps drained + dead
        members' handles.

        Probes run CONCURRENTLY (one short-lived thread per member): a
        blackholed host that hangs its probe until timeout must not
        stall death detection — or the autoscale/respawn reaction — for
        the rest of the tier."""
        with self._lock:
            targets = [(m.id, m.url, m.state)
                       for m in self._members.values()
                       if m.state in ("starting", "healthy", "draining")]
        events: list[tuple[str, str]] = []
        reap: list[ReplicaHandle] = []
        probes: dict[str, tuple[int, dict, dict | None] | OSError] = {}

        def probe(rid: str, url: str) -> None:
            try:
                code, health = self._get_json(url, "/healthz")
                statz = None
                if code == 200:
                    _, statz = self._get_json(url, "/statz")
                probes[rid] = (code, health, statz)
            except OSError as e:
                probes[rid] = e

        probe_threads = [
            threading.Thread(target=probe, args=(rid, url), daemon=True)
            for rid, url, _ in targets]
        for t in probe_threads:
            t.start()
        for t in probe_threads:
            t.join()
        for rid, url, state in targets:
            outcome = probes.get(rid)
            if isinstance(outcome, OSError):
                e = outcome
                with self._lock:
                    m = self._members.get(rid)
                    if m is None or m.state not in ("starting", "healthy",
                                                    "draining"):
                        continue
                    m.fails += 1
                    # A booting replica is expected to refuse connections
                    # while it restores + compiles — probe failures only
                    # kill members that were once reachable, or whose
                    # boot overran boot_timeout_s (crashed at startup).
                    if m.state == "starting":
                        if time.time() - m.t_added > self.boot_timeout_s:
                            self._mark_dead_locked(m, "boot timeout")
                            events.append(("replica_dead",
                                           f"{rid}: boot timeout"))
                    elif m.fails >= self.fail_after:
                        self._mark_dead_locked(m, f"health: {e!r}")
                        events.append(("replica_dead",
                                       f"{rid}: health {e!r}"))
                continue
            if outcome is None:
                continue
            code, health, statz = outcome
            with self._lock:
                m = self._members.get(rid)
                if m is None or m.state in ("dead", "stopped"):
                    continue
                if code == 503 and health.get("status") == "engine_dead":
                    self._mark_dead_locked(
                        m, health.get("error") or "engine_dead")
                    events.append(("replica_dead",
                                   f"{rid}: engine_dead"))
                    continue
                if code != 200:
                    continue
                m.fails = 0
                m.statz = statz
                m.t_statz = time.monotonic()
                if m.state == "starting":
                    m.state = "healthy"
                    events.append(("replica_up", rid))
                elif m.state == "draining":
                    empty = (m.in_flight == 0
                             and not (statz or {}).get("queue_depth")
                             and not ((statz or {}).get("engine") or {})
                             .get("active_slots"))
                    if empty:
                        m.state = "stopped"
                        reap.append(m)
                        events.append(("scale_down", f"{rid}: drained"))
        with self._lock:
            # Dead members' PROCESSES must die too: a replica declared
            # dead (engine-fatal, or fail_after missed probes) may still
            # have a live subprocess holding a full copy of the model —
            # without this, every death incident leaks one engine's
            # RAM/CPU until fleet shutdown.
            for m in self._members.values():
                if m.state == "dead" and m.handle is not None \
                        and not m.reaped:
                    m.reaped = True
                    reap.append(m)
        for m in reap:
            if self.reap_fn is not None:
                try:
                    self.reap_fn(m)
                except Exception as e:  # noqa: BLE001 — reap best-effort
                    events.append(("reap_error", f"{m.id}: {e!r}"))
        for action, reason in events:
            self._emit_fleet(action, reason=reason)

    def _respawn_once(self) -> None:
        """Replace dead members 1:1 (``respawn=True`` + ``spawn_fn``) —
        one replacement per control tick, each death replaced once."""
        if not self.respawn or self.spawn_fn is None:
            return
        with self._lock:
            victim = next((m for m in self._members.values()
                           if m.state == "dead" and not m.replaced),
                          None)
            if victim is not None:
                victim.replaced = True
        if victim is None:
            return
        try:
            rid, url, handle = self.spawn_fn()
            self.add_replica(url, handle=handle, replica_id=rid)
        except Exception as e:  # noqa: BLE001 — retried next tick
            with self._lock:
                victim.replaced = False
            self._emit_fleet("spawn_error", reason=repr(e))
            return
        with self._lock:
            self._respawns += 1
        self._emit_fleet("respawn", reason=f"{rid} replaces {victim.id}")

    def _autoscale_once(self) -> None:
        if self.autoscale is None:
            return
        with self._lock:
            live = [m for m in self._members.values()
                    if m.state in ("starting", "healthy")]
            replicas = len(live)
            burning = sorted({
                flag
                for m in live if m.statz
                for flag in (m.statz.get("slo") or {}).get("burning", ())})
            idle = all(
                m.state == "healthy" and m.in_flight == 0
                and not (m.statz or {}).get("queue_depth")
                and not ((m.statz or {}).get("engine") or {})
                .get("active_slots")
                for m in live) and bool(live)
        decision = self.autoscale.observe(
            replicas=replicas, burning=bool(burning), idle=idle)
        if decision == "up" and self.spawn_fn is not None:
            try:
                rid, url, handle = self.spawn_fn()
                self.add_replica(url, handle=handle, replica_id=rid)
            except Exception as e:  # noqa: BLE001 — retried next burn
                self._emit_fleet("spawn_error", reason=repr(e))
                return
            self._emit_fleet("scale_up",
                             reason=f"{rid}: burning {burning}")
        elif decision == "down":
            with self._lock:
                victims = sorted(
                    (m for m in self._members.values()
                     if m.state == "healthy"),
                    key=lambda m: (replica_load(m.statz) + m.in_flight,
                                   # youngest first: keep the seasoned
                                   # members' affinity maps warm
                                   -m.t_added))
                victim = victims[0] if victims else None
                if victim is not None:
                    victim.state = "draining"
                    for tenant in [t for t, rid in self._affinity.items()
                                   if rid == victim.id]:
                        del self._affinity[tenant]
            if victim is not None:
                try:
                    self._get_json(victim.url, "/healthz")  # reachability
                    req = urllib.request.Request(
                        victim.url + "/drain", data=b"{}",
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=5.0):
                        pass
                except Exception:  # noqa: BLE001 — router-side drain holds
                    pass
                self._emit_fleet("drain_begin", reason=victim.id)

    def _control_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_members_once()
                self._respawn_once()
                self._autoscale_once()
                with self._lock:
                    self._ticks += 1
                now = time.monotonic()
                if now - self._last_fleet_emit >= self.fleet_emit_every_s:
                    self._last_fleet_emit = now
                    self._emit_fleet("poll")
            except Exception:  # noqa: BLE001 — the fleet outlives a tick
                pass

    def _emit_fleet(self, action: str, reason: str = "") -> None:
        if self.telemetry is None:
            return
        with self._lock:
            members = list(self._members.values())
            replicas = sum(m.state in ("starting", "healthy", "draining")
                           for m in members)
            healthy = sum(m.state == "healthy" for m in members)
            queue_depth = sum((m.statz or {}).get("queue_depth") or 0
                              for m in members if m.state == "healthy")
            active = sum(((m.statz or {}).get("engine") or {})
                         .get("active_slots") or 0
                         for m in members if m.state == "healthy")
            step = self._ticks
        self.telemetry.emit(
            "fleet", step=step, replicas=replicas, healthy=healthy,
            queue_depth=queue_depth, active_slots=active, action=action,
            reason=reason[:300])

    # ------------------------------------------------------------ views

    def stats(self) -> dict:
        """The router's own ``/statz`` (role-tagged so a watcher knows it
        is NOT a single server's snapshot)."""
        with self._lock:
            members = list(self._members.values())
            out = {
                "role": "router",
                "replicas": len(members),
                "healthy": sum(m.state == "healthy" for m in members),
                "starting": sum(m.state == "starting" for m in members),
                "dead": sum(m.state == "dead" for m in members),
                "routed": self._routed_total,
                "served": self._served_total,
                "failed": self._failed_total,
                "failovers": self._failover_total,
                "spills": self._spill_total,
                "respawns": self._respawns,
                "max_failover_ms": round(self._max_failover_ms, 3),
                "queue_depth": sum(
                    (m.statz or {}).get("queue_depth") or 0
                    for m in members if m.state == "healthy"),
                "active_slots": sum(
                    ((m.statz or {}).get("engine") or {})
                    .get("active_slots") or 0
                    for m in members if m.state == "healthy"),
                "tenant_affinity": dict(self._affinity),
            }
        if self.autoscale is not None:
            out["autoscale"] = self.autoscale.snapshot()
        tracer = tracing.active()
        if tracer is not None and tracer.buffer is not None:
            out["serve_trace_sampled"] = tracer.buffer.stats()
        return out

    def fleet_snapshot(self) -> dict:
        """The ``/fleetz`` payload: router stats + per-member views —
        ``tools/watch_serve.py --fleet``'s one-poll feed."""
        with self._lock:
            members = [m.view() for m in sorted(
                self._members.values(), key=lambda m: m.id)]
        return {"router": self.stats(), "members": members}

    # -------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        assert self._http is not None, "start() first"
        return self._http.server_address[1]

    def start(self) -> None:
        self._http = ThreadingHTTPServer((self._host, self._port),
                                         self._make_handler())
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="router-http")
        self._http_thread.start()
        self._control = threading.Thread(
            target=self._control_loop, daemon=True, name="router-control")
        self._control.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        if self._control is not None:
            self._control.join(timeout=10.0)

    # ------------------------------------------------------------- HTTP

    def _make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet server
                pass

            def _reply_json(self, code: int, payload: dict) -> None:
                self._reply_raw(code, json.dumps(payload).encode())

            def _reply_raw(self, code: int, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    stats = router.stats()
                    if stats["healthy"] == 0:
                        return self._reply_json(503, {
                            "status": "no_healthy_replica", **stats})
                    return self._reply_json(200, {"status": "ok",
                                                  **stats})
                if self.path == "/statz":
                    return self._reply_json(200, router.stats())
                if self.path == "/fleetz":
                    return self._reply_json(200, router.fleet_snapshot())
                return self._reply_json(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path != "/generate":
                    return self._reply_json(404,
                                            {"error": "unknown path"})
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) or b"{}"
                try:
                    tenant = str(json.loads(body).get(
                        "tenant", "default"))
                except (ValueError, AttributeError):
                    # Forward anyway under the default tenant — the
                    # replica owns request validation (400s it).
                    tenant = "default"
                status, payload = router.route(
                    body, tenant, wire=tracing.parse_wire(self.headers))
                return self._reply_raw(status, payload)

        return Handler
