"""Multi-tenant admission control + fair scheduling for the decode engine.

Two jobs, both at the REQUEST granularity (the engine schedules tokens;
this module schedules whose request gets the next free slot):

- **Admission control / backpressure** — every tenant owns a bounded
  queue; a submit past the bound raises :class:`QueueFull`, which the
  frontend maps to HTTP 429 (the client's signal to back off).  Bounded
  queues are what keep an overloaded server's latency bounded instead of
  letting the queue — and every caller's tail latency — grow without
  limit.
- **Weighted fair ordering** — when a slot frees, the next request comes
  from the eligible tenant with the smallest *normalized service*
  (served tokens / weight): start-time fair queuing over token service.
  A flooding tenant saturates its share; a light tenant's occasional
  request schedules at the front because its normalized service lags.
  New tenants join at the CURRENT minimum service (not zero) so an
  idle-then-bursty tenant cannot claim infinite catch-up credit.

Thread-safe: HTTP handler threads submit; the engine thread pops.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Callable

#: Tenant a request lands in when it names none.
DEFAULT_TENANT = "default"


class QueueFull(RuntimeError):
    """The tenant's queue is at its bound — backpressure (HTTP 429)."""


@dataclasses.dataclass
class TenantConfig:
    name: str
    weight: float = 1.0          # share of service under contention
    max_queue: int = 64          # queued (not yet admitted) request bound

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        if self.max_queue < 1:
            raise ValueError(f"tenant {self.name!r}: max_queue must be >= 1")


class Request:
    """One generate request's lifecycle record (queue -> slot -> done)."""

    _ids = itertools.count()

    def __init__(self, prompt: list[int], num_tokens: int, *,
                 tenant: str = DEFAULT_TENANT, eos_id: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 speculative: bool = False):
        self.id = next(Request._ids)
        self.tenant = tenant
        self.prompt = [int(t) for t in prompt]
        self.num_tokens = int(num_tokens)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        # Opt-in to the engine's speculative decode arm (greedy-only;
        # honored when the server runs with spec_k >= 2, plain decode
        # otherwise — token-for-token identical either way).
        self.speculative = bool(speculative)
        self.spec_rounds = 0              # engine steps this lane rode
        self.tokens: list[int] = []       # generated tokens (appended live)
        self.error: str | None = None
        self.abandoned = False            # caller gave up; retire early
        self.event = threading.Event()    # set on completion/error
        # Latency waypoints (perf_counter seconds).  t_submit_unix is the
        # epoch twin of t_submit: request spans need absolute timestamps
        # so tools/export_trace.py can place them on the cluster timeline
        # (perf_counter is process-relative).
        self.t_submit = time.perf_counter()
        self.t_submit_unix = time.time()
        self.t_admit: float | None = None
        self.t_first_token: float | None = None
        self.t_done: float | None = None
        # Tracing anchors (utils/tracing.py): the root span id is
        # pre-allocated at first touch by a tracer-aware stage (queue pop
        # or admission) so children emitted live can parent under it; the
        # root span itself is emitted at retirement.
        self.span_root = 0
        self.trace: str | None = None     # "<run_id>/req<id>" when traced
        # Cross-tier wire context (X-DTF-* headers, docs/observability.md
        # "Cross-tier tracing"): wire_parent is the upstream tier's span
        # id the engine's root serve.request span nests under (0 = this
        # process IS the root); trace_forced means an upstream tier
        # already ruled the trace interesting, so the tail sampler must
        # keep it regardless of the local verdict.
        self.wire_parent = 0
        self.trace_forced = False

    # Derived latency figures (ms); None until the waypoint exists.
    @property
    def queue_ms(self) -> float | None:
        if self.t_admit is None:
            return None
        return (self.t_admit - self.t_submit) * 1e3

    @property
    def ttft_ms(self) -> float | None:
        """Time to first token, from SUBMIT (queue wait included — that is
        the latency the caller feels)."""
        if self.t_first_token is None:
            return None
        return (self.t_first_token - self.t_submit) * 1e3

    @property
    def tpot_ms(self) -> float | None:
        """Time per output token after the first (decode cadence)."""
        if (self.t_done is None or self.t_first_token is None
                or len(self.tokens) < 2):
            return None
        return ((self.t_done - self.t_first_token) * 1e3
                / (len(self.tokens) - 1))

    @property
    def e2e_ms(self) -> float | None:
        """Submit-to-done latency — the figure e2e SLOs are written on."""
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3


class _TenantState:
    __slots__ = ("config", "queue", "served_tokens", "admitted",
                 "rejected", "completed", "queued_hwm", "abandoned")

    def __init__(self, config: TenantConfig):
        self.config = config
        self.queue: collections.deque[Request] = collections.deque()
        self.served_tokens = 0.0   # service accounted so far
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.queued_hwm = 0        # queue-depth high-water mark
        self.abandoned = 0         # caller-gave-up retirements


class FairScheduler:
    """Bounded per-tenant queues + weighted min-service request pop."""

    def __init__(self, tenants: list[TenantConfig] | None = None,
                 default_max_queue: int = 64):
        self._lock = threading.Lock()
        self._default_max_queue = int(default_max_queue)
        self._tenants: dict[str, _TenantState] = {}
        self._depth_hwm = 0
        for cfg in tenants or ():
            self._tenants[cfg.name] = _TenantState(cfg)

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            # Unknown tenants are first-class (multi-tenant without
            # preregistration): default weight, default bound, and service
            # starting at the current minimum so they get no retroactive
            # catch-up credit.
            st = _TenantState(TenantConfig(
                tenant, max_queue=self._default_max_queue))
            floor = min((t.served_tokens / t.config.weight
                         for t in self._tenants.values()), default=0.0)
            st.served_tokens = floor * st.config.weight
            self._tenants[tenant] = st
        return st

    def submit(self, request: Request) -> None:
        """Queue the request, or raise :class:`QueueFull` (backpressure)."""
        with self._lock:
            st = self._state(request.tenant)
            if len(st.queue) >= st.config.max_queue:
                st.rejected += 1
                raise QueueFull(
                    f"tenant {request.tenant!r} queue is at its bound "
                    f"({st.config.max_queue}); retry with backoff")
            st.queue.append(request)
            st.queued_hwm = max(st.queued_hwm, len(st.queue))
            self._depth_hwm = max(self._depth_hwm, sum(
                len(t.queue) for t in self._tenants.values()))

    def next_request(self, admissible: Callable[[Request], bool]
                     = lambda r: True) -> Request | None:
        """Pop the head request of the min-normalized-service tenant whose
        head passes ``admissible`` (e.g. "fits the free KV pages").

        ``admissible`` runs UNDER the scheduler lock (the admissibility
        check and the pop must be atomic against concurrent submits), so
        it must be a cheap, lock-ordered predicate: it may take locks
        that are leaves in the acquisition order (the engine's
        ``can_admit`` -> ``PageAllocator`` lock) and must never call
        back into the scheduler — dtflint's lock-callback rule flags
        this site, baselined with exactly this contract, and a violating
        caller shows up under ``DTF_LOCKCHECK=1``.

        Heads that were abandoned while queued are dropped in passing.
        Head-of-line only — a tenant's own requests stay FIFO (its second
        request must not overtake its first into a freed slot)."""
        with self._lock:
            ranked = sorted(
                (st for st in self._tenants.values() if st.queue),
                key=lambda st: st.served_tokens / st.config.weight)
            for st in ranked:
                while st.queue and st.queue[0].abandoned:
                    st.queue.popleft()
                    st.abandoned += 1
                if st.queue and admissible(st.queue[0]):
                    st.admitted += 1
                    return st.queue.popleft()
            return None

    def account(self, tenant: str, tokens: int) -> None:
        """Charge generated tokens to the tenant's service total."""
        with self._lock:
            self._state(tenant).served_tokens += tokens

    def complete(self, tenant: str) -> None:
        with self._lock:
            self._state(tenant).completed += 1

    def note_abandoned(self, tenant: str) -> None:
        """Count an abandoned-caller retirement against the tenant (the
        engine retires the lane; this keeps the per-tenant books)."""
        with self._lock:
            self._state(tenant).abandoned += 1

    def drain(self) -> list[Request]:
        """Empty every queue and return the popped requests (fatal
        shutdown path).  Deliberately does NOT touch the admitted/
        completed tallies — these requests were never served, and a
        /statz scrape of the dead-but-still-listening server must not
        report them as if they were."""
        with self._lock:
            out: list[Request] = []
            for st in self._tenants.values():
                out.extend(st.queue)
                st.queue.clear()
            return out

    def depth(self) -> int:
        with self._lock:
            return sum(len(st.queue) for st in self._tenants.values())

    def depth_hwm(self) -> int:
        """All-tenants queue-depth high-water mark since startup."""
        with self._lock:
            return self._depth_hwm

    def stats(self) -> dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "weight": st.config.weight,
                    "max_queue": st.config.max_queue,
                    "queued": len(st.queue),
                    "queued_hwm": st.queued_hwm,
                    "admitted": st.admitted,
                    "completed": st.completed,
                    "rejected": st.rejected,
                    "abandoned": st.abandoned,
                    "served_tokens": int(st.served_tokens),
                }
                for name, st in sorted(self._tenants.items())
            }


def parse_tenants(spec: str) -> list[TenantConfig]:
    """``"name[:weight[:max_queue]],..."`` -> tenant configs (the CLI
    flag format; an empty spec configures nothing — tenants then
    self-register at defaults on first request)."""
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        fields = part.split(":")
        if len(fields) > 3 or not fields[0]:
            raise ValueError(f"bad tenant spec {part!r}; "
                             "want name[:weight[:max_queue]]")
        cfg = TenantConfig(
            fields[0],
            weight=float(fields[1]) if len(fields) > 1 else 1.0,
            max_queue=int(fields[2]) if len(fields) > 2 else 64)
        out.append(cfg)
    return out
