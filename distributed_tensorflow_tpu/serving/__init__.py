"""Serving tier: continuous-batching multi-tenant decode over the
cluster plane (docs/serving.md).

- :mod:`.engine` — slot-batched resident decode step + paged KV pool;
- :mod:`.kv_pool` — page allocator (the pool's host-side bookkeeping);
- :mod:`.scheduler` — per-tenant bounded queues + weighted fair ordering;
- :mod:`.server` / :mod:`.client` — HTTP frontend and thin client;
- :mod:`.router` — N replicas behind one statz-routed, SLO-autoscaled
  frontend (docs/serving.md, "Fleet");
- :mod:`.hot_swap` — checkpoint-plane watcher feeding atomic weight swaps;
- :mod:`.slo` — per-tenant objectives, sliding windows, burn-rate alerts
  (docs/observability.md, "Serving tracing & SLOs").

Imports stay lazy at this level: the package is importable without jax
initialized (the client, allocator, router, and SLO engine are pure host
code).
"""

from .kv_pool import OutOfPages, PageAllocator
from .router import AutoscalePolicy, Router, choose_replica, replica_load
from .scheduler import (DEFAULT_TENANT, FairScheduler, QueueFull, Request,
                        TenantConfig, parse_tenants)
from .slo import Objective, SloEngine, parse_slos

__all__ = [
    "AutoscalePolicy", "DEFAULT_TENANT", "FairScheduler", "Objective",
    "OutOfPages", "PageAllocator", "QueueFull", "Request", "Router",
    "SloEngine", "TenantConfig", "choose_replica", "parse_slos",
    "parse_tenants", "replica_load",
]
