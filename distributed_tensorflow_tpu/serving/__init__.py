"""Serving tier: continuous-batching multi-tenant decode over the
cluster plane (docs/serving.md).

- :mod:`.engine` — slot-batched resident decode step + paged KV pool;
- :mod:`.kv_pool` — page allocator (the pool's host-side bookkeeping);
- :mod:`.scheduler` — per-tenant bounded queues + weighted fair ordering;
- :mod:`.server` / :mod:`.client` — HTTP frontend and thin client;
- :mod:`.hot_swap` — checkpoint-plane watcher feeding atomic weight swaps.

Imports stay lazy at this level: the package is importable without jax
initialized (the client and allocator are pure host code).
"""

from .kv_pool import OutOfPages, PageAllocator
from .scheduler import (DEFAULT_TENANT, FairScheduler, QueueFull, Request,
                        TenantConfig, parse_tenants)

__all__ = [
    "DEFAULT_TENANT", "FairScheduler", "OutOfPages", "PageAllocator",
    "QueueFull", "Request", "TenantConfig", "parse_tenants",
]
