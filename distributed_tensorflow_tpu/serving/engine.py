"""Continuous-batching decode engine — one resident jitted step, a slot
batch, and a paged KV pool (the serving half of docs/serving.md).

The engine owns a FIXED batch of ``num_slots`` decode lanes and per-layer
paged KV pools (``models/gpt.init_kv_pool``).  Admission and retirement
happen PER STEP, not per batch: a new request prefills into freshly
allocated pages and joins the slot batch while other lanes are mid-decode;
a finished lane frees its pages and the slot the same step it emits eos or
exhausts its budget.  Because the decode step's shapes never depend on
which slots are live (idle lanes ride along with sentinel page tables —
their writes drop, their outputs are ignored), the WHOLE serving lifetime
runs two compiled programs: one prefill per prompt-page-count bucket
(LRU-bounded at ``prefill_cache_cap`` resident programs) and ONE decode
step, resident from the first request to the last.  With
``prefill_chunk >= 1`` the per-bucket prefill programs give way to ONE
resident chunk-prefill program: a long prompt no longer stalls every
live decode lane for a full compile-bucket forward — the prefilling
lane occupies its slot as a masked passenger and advances
``prefill_chunk`` prompt positions per engine step while the other
lanes keep decoding (docs/serving.md, "Chunked prefill").  With
``spec_k >= 2`` a third resident program joins them — a spec_k-wide
``decode_chunk_paged`` verify used whenever at least one active lane
opted into speculation (docs/speculative.md): speculative lanes emit
their accepted draft prefix + bonus token per step, plain lanes ride the
same dispatch and emit exactly their node-0 sample.

Weight handling reuses the inference-side levers already in-tree:
``quantize="int8"`` stores the swap-able tree as per-channel int8
(:mod:`..ops.quant`; dequantized inside the jitted step where XLA fuses it
into the matmuls) and ``kv_dtype="float8"`` keeps the pools in
``float8_e4m3fn`` (upcast on read).  Hot model swap
(:meth:`DecodeEngine.swap_params`) stages a prepared tree off-thread and
the engine adopts it BETWEEN steps: in-flight sequences keep their KV
pages and simply continue under the new weights — no drain, no drop.

Single-threaded by contract: exactly one thread (the server's engine
loop) calls :meth:`admit` / :meth:`step`; :meth:`swap_params` may be
called from any thread.  Buffers are not donated to the jitted step — the
test/bench environment is CPU, where donation only warns; flipping
``donate_argnums`` on for the pool argument is the first TPU-side lever.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any

import numpy as np

from ..models import gpt as gpt_lib
from ..models.drafting import NGramIndex
from ..ops.quant import (load_inference_tree, prepare_inference_tree,
                         resolve_kv_dtype, validate_quantize)
from ..utils import tracing
from .kv_pool import PageAllocator, reservation_tokens
from .scheduler import Request


def _unix_at(perf_t: float) -> float:
    """Map a ``perf_counter`` stamp onto the epoch clock (spans carry
    ``t_unix`` so the exporter can align them across hosts)."""
    return time.time() - (time.perf_counter() - perf_t)


def _ensure_request_trace(tracer, request: Request) -> None:
    """Give the request its trace identity on first tracer contact: a
    pre-allocated root span id (children parent under it live; the root
    ``serve.request`` span is emitted at retirement) and the
    ``"<run_id>/req<id>"`` trace id every span of this request carries."""
    if not request.span_root:
        request.span_root = tracer.allocate_id()
        request.trace = tracer.request_trace_id(request.id)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Decode-engine geometry and weight-path knobs."""

    num_slots: int = 4            # resident decode lanes (batch dim)
    page_size: int = 16           # token slots per KV page
    num_pages: int = 128          # pool pages per layer
    max_pages_per_seq: int = 8    # page-table width (caps seq length)
    quantize: str = ""            # "" | "int8" weight storage
    kv_dtype: str = ""            # "" | "bfloat16" | "float8" pool dtype
    # Speculative decode arm (docs/speculative.md): 0 disables; >= 2
    # compiles a second resident step — a spec_k-wide decode_chunk_paged
    # verify — used whenever at least one active lane opted in
    # (Request.speculative).  Per-slot prompt-lookup drafts come from the
    # shared incremental n-gram index (models/drafting.py).
    spec_k: int = 0
    spec_ngram: int = 3
    # Chunked prefill (docs/serving.md, "Chunked prefill"): 0 = legacy
    # whole-bucket prefill at admission (the prompt stalls every live
    # decode lane for one full compile-bucket forward); >= 1 = a
    # prefilling lane occupies its slot and advances `prefill_chunk`
    # prompt tokens per engine step through ONE resident chunk program
    # while the other lanes keep decoding.
    prefill_chunk: int = 0
    # Bound on the per-bucket prefill compile cache (whole-bucket path):
    # adversarial prompt-length mixes otherwise pin one jitted program
    # per page count for the process lifetime.  LRU eviction beyond it.
    prefill_cache_cap: int = 8

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    def __post_init__(self):
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        validate_quantize(self.quantize)
        resolve_kv_dtype(self.kv_dtype)  # validates
        if self.spec_k == 1 or self.spec_k < 0:
            raise ValueError(f"spec_k must be 0 (off) or >= 2, "
                             f"got {self.spec_k}")
        if self.spec_k and self.spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, "
                             f"got {self.spec_ngram}")
        if self.prefill_chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, "
                             f"got {self.prefill_chunk}")
        if self.prefill_cache_cap < 1:
            raise ValueError(f"prefill_cache_cap must be >= 1, "
                             f"got {self.prefill_cache_cap}")


class _Slot:
    """One live sequence's lane state (host side)."""

    __slots__ = ("request", "prompt_len", "budget", "generated", "spec",
                 "history", "hist_len", "index", "table", "prefill_pos",
                 "prefill_target", "prefill_chunks", "prefill_pages",
                 "t_prefill_start")

    def __init__(self, request: Request, spec_ngram: int = 0):
        self.request = request
        self.prompt_len = len(request.prompt)
        self.budget = request.num_tokens
        self.generated = 0
        # Chunked-prefill bookkeeping: positions [prefill_pos,
        # prefill_target) of the prompt still owe their K/V to the pool.
        # target stays 0 on the whole-bucket path (never prefilling).
        self.table = None            # full page table, np [MP]
        self.prefill_pos = 0
        self.prefill_target = 0
        self.prefill_chunks = 0
        self.prefill_pages = 0
        self.t_prefill_start = 0.0
        # Speculative lanes keep their token history + an incremental
        # n-gram index on the host; drafting is O(ngram + k) per step.
        self.spec = bool(spec_ngram)
        if self.spec:
            self.history = np.zeros(self.prompt_len + self.budget,
                                    np.int32)
            self.history[:self.prompt_len] = request.prompt
            self.hist_len = self.prompt_len
            self.index = NGramIndex(spec_ngram)
            self.index.update(self.history, self.hist_len - 1)
        else:
            self.history = None
            self.hist_len = 0
            self.index = None

    @property
    def prefilling(self) -> bool:
        """Lane seated but its prompt K/V not yet fully resident — it
        rides the decode batch as a masked passenger (sentinel table)
        and advances by chunks instead of emitting tokens."""
        return self.prefill_pos < self.prefill_target

    def draft(self, k: int) -> np.ndarray:
        """[k] drafted continuation tokens for the lane's current tail."""
        return self.index.draft(self.history, self.hist_len, k)

    def commit(self, tokens: list[int]) -> None:
        """Fold tokens emitted this step into history + index (the last
        token stays un-indexed so the next tail can't self-match)."""
        n = len(tokens)
        self.history[self.hist_len:self.hist_len + n] = tokens
        self.hist_len += n
        self.index.update(self.history, self.hist_len - 1)


class DecodeEngine:
    """Slot-batched continuous decoding over a paged KV pool."""

    def __init__(self, model: gpt_lib.GptLM, params: Any,
                 config: EngineConfig | None = None, telemetry=None):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.model = model
        self.config = cfg = config or EngineConfig()
        self.telemetry = telemetry
        mcfg = model.cfg
        if mcfg.attention_window:
            raise ValueError("the paged serving engine needs full-cache "
                             "addressing; sliding-window checkpoints are "
                             "not pageable")
        # Positions must stay addressable by the position table (rope-less
        # checkpoints) — the engine's logical capacity is the tighter of
        # the page-table span and the model's max_position.
        self.capacity = min(cfg.max_seq_len, mcfg.max_position)
        self._cache_dtype = resolve_kv_dtype(cfg.kv_dtype)
        self._tree = self._prepare_params(params)
        self._pending: tuple[Any, int] | None = None  # (tree, label step)
        self.model_step = 0            # checkpoint step the weights carry
        self.swaps = 0
        self.pools = gpt_lib.init_kv_pool(
            mcfg, cfg.num_pages, cfg.page_size, dtype=self._cache_dtype)
        self.allocator = PageAllocator(cfg.num_pages, cfg.page_size)

        B, MP = cfg.num_slots, cfg.max_pages_per_seq
        self._slots: list[_Slot | None] = [None] * B
        self._tokens = np.zeros((B,), np.int32)
        self._positions = np.zeros((B,), np.int32)
        self._tables = np.full((B, MP), cfg.num_pages, np.int32)
        self._temp = np.zeros((B,), np.float32)
        self._top_k = np.zeros((B,), np.int32)
        self._top_p = np.zeros((B,), np.float32)
        self._seeds = np.zeros((B,), np.int32)

        self.step_index = 0
        self._admitted_since_step = 0
        self._spec_accepted_since_step = 0
        self._spec_rows_last_step = 0
        self._step_fn = self._build_step()
        self._spec_step_fn = (self._build_spec_step()
                              if cfg.spec_k else None)
        # Per-bucket prefill programs, LRU-bounded (prefill_cache_cap);
        # the chunk-prefill program is memoized per chunk width (one in
        # practice — the width is an engine constant).
        self._prefill_fns: collections.OrderedDict[int, Any] = \
            collections.OrderedDict()
        self._prefill_evictions = 0
        self._chunk_fns: dict[int, Any] = {}
        # Cumulative milliseconds spent producing prompt K/V (bulk
        # prefill calls + chunk dispatches) — the bench's
        # `prefill_stall_ms` decomposition reads this.
        self.prefill_ms_total = 0.0

    # ------------------------------------------------------------ params

    def _prepare_params(self, params):
        """Host tree -> device-resident serving tree (int8 when asked) —
        the shared prepare/load recipe of ops/quant.py."""
        return self._jax.tree.map(
            self._jnp.asarray,
            prepare_inference_tree(params, self.config.quantize))

    def _dequant(self, tree):
        return load_inference_tree(tree, self.config.quantize,
                                   self._jnp.dtype(self.model.cfg.dtype))

    def swap_params(self, params, step: int = 0) -> None:
        """Stage new weights for adoption between engine steps.

        Safe from any thread: preparation (quantize + device transfer)
        runs HERE, on the caller; the engine thread's next step just swaps
        a reference.  In-flight sequences keep decoding — their KV pages
        were computed under the old weights, the continuation runs under
        the new (the standard continuous-batching swap semantics;
        docs/serving.md#hot-swap)."""
        prepared = self._prepare_params(params)
        self._pending = (prepared, int(step))

    def apply_pending_swap(self) -> bool:
        """Adopt staged weights (engine thread, between steps)."""
        pending = self._pending
        if pending is None:
            return False
        t0 = time.perf_counter()
        self._pending = None
        tree, step = pending
        self._tree = tree
        prev = self.model_step
        self.model_step = step
        self.swaps += 1
        if self.telemetry is not None:
            self.telemetry.counter("serve_swaps").inc()
            self.telemetry.emit(
                "model_swap", step=self.step_index,
                from_model_step=prev, to_model_step=step,
                in_flight=self.active_slots)
        tracer = tracing.active()
        if tracer is not None:
            # The adoption pause, stamped once at the engine level AND
            # onto every in-flight request's trace: a request whose decode
            # straddled a hot swap shows the pause inside its own span
            # tree, so "this stream hiccuped because a swap landed" needs
            # no cross-referencing.
            dur_ms = (time.perf_counter() - t0) * 1e3
            t_unix = _unix_at(t0)
            swap_id = tracer.emit_span(
                "serve.swap", t_unix, dur_ms, step=self.step_index,
                parent_id=0, from_model_step=prev, to_model_step=step,
                in_flight=self.active_slots)
            for state in self._slots:
                if state is None:
                    continue
                req = state.request
                _ensure_request_trace(tracer, req)
                tracer.emit_span(
                    "serve.swap_pause", t_unix, dur_ms,
                    step=self.step_index,
                    parent_id=req.span_root or swap_id, trace=req.trace,
                    request_id=req.id, tenant=req.tenant,
                    from_model_step=prev, to_model_step=step)
        return True

    # ----------------------------------------------------- jitted bodies

    def _build_step(self):
        jax, jnp = self._jax, self._jnp
        model = self.model

        def step(tree, tokens, positions, tables, pools, temp, tk, tp,
                 seeds):
            params = self._dequant(tree)
            logits, pools = model.apply(
                {"params": params}, tokens, pools, tables, positions,
                method=gpt_lib.GptLM.decode_paged)
            # Per-row keys folded on the ABSOLUTE index being generated:
            # a sampled stream is reproducible for its (seed, position)s
            # no matter which other requests shared the batch.
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.key(s), p))(
                    seeds, positions + 1)
            nxt = gpt_lib.sample_logits_dynamic(logits, keys, temp, tk, tp)
            return nxt, pools

        return jax.jit(step)

    def _build_spec_step(self):
        """The speculative arm's resident step: ONE decode_chunk_paged
        verify over the whole slot batch.  Chunk column 0 is each lane's
        current token (so ``logits[:, 0]`` is exactly what the plain step
        computes — non-speculative rows sample from it with identical
        per-row keys and keep token parity); columns 1.. are drafts,
        verified against the greedy argmaxes on device.  Rejected page
        writes stay masked by the per-row frontier until real tokens
        overwrite them."""
        jax, jnp = self._jax, self._jnp
        model = self.model

        def spec_step(tree, chunk, positions, tables, pools, temp, tk, tp,
                      seeds):
            params = self._dequant(tree)
            logits, pools = model.apply(
                {"params": params}, chunk, pools, tables, positions,
                method=gpt_lib.GptLM.decode_chunk_paged)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.key(s), p))(
                    seeds, positions + 1)
            sampled0 = gpt_lib.sample_logits_dynamic(
                logits[:, 0], keys, temp, tk, tp)
            return greedy, sampled0, pools

        return jax.jit(spec_step)

    def _prefill_fn(self, n_pages: int):
        """Jitted prompt prefill writing straight into the pool; one
        compilation per prompt-page-count, LRU-bounded at
        ``prefill_cache_cap`` resident programs (an adversarial mix of
        prompt lengths would otherwise grow one jitted program per page
        count for the process lifetime — the `serve_compile_cache`
        gauge watches the resident count)."""
        fn = self._prefill_fns.get(n_pages)
        if fn is not None:
            self._prefill_fns.move_to_end(n_pages)
            return fn
        jax = self._jax
        model, mcfg = self.model, self.model.cfg
        page = self.config.page_size
        p_len = n_pages * page

        def prefill(tree, tokens, pools, phys):
            params = self._dequant(tree)
            caches = gpt_lib.init_kv_cache(mcfg, 1, p_len,
                                           dtype=self._cache_dtype)
            _, caches = model.apply({"params": params}, tokens, caches,
                                    method=gpt_lib.GptLM.prefill)
            new_pools = []
            for (kc, vc), (kp, vp) in zip(caches, pools):
                kp = kp.at[phys].set(
                    kc[0].reshape(n_pages, page, *kc.shape[2:]),
                    mode="drop")
                vp = vp.at[phys].set(
                    vc[0].reshape(n_pages, page, *vc.shape[2:]),
                    mode="drop")
                new_pools.append((kp, vp))
            return new_pools

        fn = jax.jit(prefill)
        self._prefill_fns[n_pages] = fn
        while len(self._prefill_fns) > self.config.prefill_cache_cap:
            self._prefill_fns.popitem(last=False)
            self._prefill_evictions += 1
        return fn

    def _chunk_prefill_fn(self, chunk: int):
        """Jitted chunk-prefill program (``GptLM.prefill_chunk_paged``):
        C prompt tokens per prefilling row against the paged pool, no LM
        head.  ONE resident compilation per chunk width for the engine
        lifetime — memoized exactly like :meth:`_prefill_fn` so the
        BENCH_r04 per-call retrace class cannot ride back in through
        this builder (the dtflint jit-hygiene fixture pins this shape)."""
        fn = self._chunk_fns.get(chunk)
        if fn is not None:
            return fn
        jax = self._jax
        model = self.model

        def chunk_prefill(tree, tokens, positions, tables, pools):
            params = self._dequant(tree)
            return model.apply(
                {"params": params}, tokens, pools, tables, positions,
                method=gpt_lib.GptLM.prefill_chunk_paged)

        fn = jax.jit(chunk_prefill)
        self._chunk_fns[chunk] = fn
        return fn

    # -------------------------------------------------------- admission

    @property
    def active_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def free_slots(self) -> int:
        return self.config.num_slots - self.active_slots

    def validate(self, request: Request) -> None:
        """Reject malformed requests up front (HTTP 400 territory)."""
        vocab = self.model.cfg.vocab_size
        if not request.prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < vocab for t in request.prompt):
            raise ValueError(f"prompt token out of range [0, {vocab})")
        if request.num_tokens < 1:
            raise ValueError("num_tokens must be >= 1")
        if request.eos_id is not None and not (
                0 <= request.eos_id < vocab):
            raise ValueError(f"eos_id must be in [0, {vocab})")
        if not 0.0 <= request.top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        # top_k / seed land in int32 slot arrays — an unbounded value
        # would raise OverflowError inside admit(), which the engine
        # loop's catch-all turns into failing EVERY in-flight stream.
        if not 0 <= request.top_k < 2 ** 31:
            raise ValueError("top_k must be in [0, 2**31)")
        if not 0 <= request.seed < 2 ** 31:
            raise ValueError("seed must be in [0, 2**31)")
        if request.speculative and request.temperature > 0.0:
            raise ValueError(
                "speculative decoding is greedy-only (acceptance compares "
                "against argmax); drop temperature or the speculative flag")
        total = len(request.prompt) + request.num_tokens
        if total > self.capacity:
            raise ValueError(
                f"prompt + num_tokens = {total} exceeds the engine "
                f"capacity {self.capacity} (pages x page_size, capped by "
                f"the model's max_position)")
        # A worst-case reservation larger than the whole pool would pass
        # the capacity check on small pools yet never become admissible —
        # the request would pin its tenant's queue head until timeout.
        need = self.allocator.pages_for(
            reservation_tokens(len(request.prompt), request.num_tokens))
        if need > self.config.num_pages:
            raise ValueError(
                f"request reserves {need} KV page(s) worst-case but the "
                f"pool only has {self.config.num_pages}")

    def can_admit(self, request: Request) -> bool:
        """Slot and KV pages available right now (the scheduler's
        admissibility predicate; assumes :meth:`validate` passed)."""
        if self.free_slots < 1:
            return False
        return self.allocator.can_alloc(
            reservation_tokens(len(request.prompt), request.num_tokens))

    def admit(self, request: Request) -> int:
        """Prefill the prompt into fresh pages and seat the request.

        The first GENERATED token comes from the next :meth:`step` — the
        lane is seeded with the last prompt token at position P-1, so the
        resident decode step produces token P like any other step (one
        program for every token)."""
        cfg = self.config
        slot = next(i for i, s in enumerate(self._slots) if s is None)
        P = len(request.prompt)
        tracer = tracing.active()
        if tracer is not None:
            _ensure_request_trace(tracer, request)
        t_res = time.perf_counter()
        pages = self.allocator.alloc(
            request.id, reservation_tokens(P, request.num_tokens))
        t_pre = time.perf_counter()
        if tracer is not None:
            tracer.emit_span(
                "serve.reserve", _unix_at(t_res), (t_pre - t_res) * 1e3,
                step=self.step_index, parent_id=request.span_root,
                trace=request.trace, request_id=request.id,
                tenant=request.tenant, pages=len(pages))
        n_prefill = self.allocator.pages_for(P)
        chunked = cfg.prefill_chunk > 0
        if not chunked:
            # Whole-bucket prefill (legacy): one forward over the whole
            # padded prompt bucket, blocking this engine step for its
            # full duration — a never-seen page count pays its fresh
            # bucket compile here too.
            try:
                p_len = n_prefill * cfg.page_size
                toks = np.zeros((1, p_len), np.int32)
                toks[0, :P] = request.prompt
                phys = np.asarray(pages[:n_prefill], np.int32)
                self.pools = self._prefill_fn(n_prefill)(
                    self._tree, self._jnp.asarray(toks), self.pools,
                    self._jnp.asarray(phys))
            except Exception:
                self.allocator.free(request.id)
                raise
            # Block before timing, like _advance_prefill: on an async
            # backend the call above returns at dispatch and the
            # prefill's device time would otherwise be absorbed into the
            # next decode step — the stall decomposition (and the
            # serve.prefill span) must record device time on both paths.
            self._jax.block_until_ready(self.pools)
            self.prefill_ms_total += (time.perf_counter() - t_pre) * 1e3
            if tracer is not None:
                # chunks=1: the whole bucket landed in one dispatch —
                # the chunked path's spans count theirs instead.
                tracer.emit_span(
                    "serve.prefill", _unix_at(t_pre),
                    (time.perf_counter() - t_pre) * 1e3,
                    step=self.step_index, parent_id=request.span_root,
                    trace=request.trace, request_id=request.id,
                    tenant=request.tenant, bucket=n_prefill,
                    pages=n_prefill, prompt_tokens=P, chunks=1)
        spec = bool(cfg.spec_k) and request.speculative
        state = _Slot(request, cfg.spec_ngram if spec else 0)
        state.table = self.allocator.page_table(request.id,
                                                cfg.max_pages_per_seq)
        state.prefill_pages = n_prefill
        self._slots[slot] = state
        if chunked and P > 1:
            # The lane seats in PREFILLING state: its row keeps the
            # sentinel page table (decode-batch writes drop, outputs
            # ignored — exactly an idle lane) while step() advances the
            # prompt `prefill_chunk` positions per engine step.  Only
            # positions [0, P-1) owe K/V — the decode step writes P-1
            # itself, same as the whole-bucket seed.
            state.prefill_target = P - 1
            state.t_prefill_start = t_pre
        else:
            # Whole-bucket path, or a chunked P == 1 prompt: nothing
            # owes K/V (the decode step writes position 0 itself), so
            # the lane goes live immediately — no program runs, no
            # serve.prefill span (nothing prefilled).
            self._tables[slot] = state.table
        self._tokens[slot] = request.prompt[-1]
        self._positions[slot] = P - 1
        self._temp[slot] = request.temperature
        self._top_k[slot] = request.top_k
        self._top_p[slot] = request.top_p
        self._seeds[slot] = request.seed
        self._admitted_since_step += 1
        request.t_admit = time.perf_counter()
        return slot

    def _retire(self, slot: int, status: str) -> Request:
        state = self._slots[slot]
        assert state is not None
        req = state.request
        self._slots[slot] = None
        self._tables[slot] = self.config.num_pages
        self._tokens[slot] = 0
        self._positions[slot] = 0
        self._temp[slot] = 0.0
        self._top_k[slot] = 0
        self._top_p[slot] = 0.0
        self._seeds[slot] = 0
        self.allocator.free(req.id)
        req.t_done = time.perf_counter()
        if self.telemetry is not None:
            tel = self.telemetry
            tel.counter("serve_requests").inc()
            tel.counter("serve_tokens_out").inc(len(req.tokens))
            if status == "abandoned":
                tel.counter("serve_abandoned").inc()
                tel.counter(f"serve_abandoned[{req.tenant}]").inc()
            # Global + per-tenant latency distributions: the bracketed
            # name renders as a {tenant=...} label on /metricz and feeds
            # watch_serve's per-tenant percentile columns.
            for name, value in (("serve_ttft_ms", req.ttft_ms),
                                ("serve_tpot_ms", req.tpot_ms),
                                ("serve_e2e_ms", req.e2e_ms)):
                if value is not None:
                    tel.histogram(name).record(value)
                    tel.histogram(f"{name}[{req.tenant}]").record(value)
            extra = {}
            if state.spec and req.spec_rounds:
                extra = {"speculative": True,
                         "spec_rounds": req.spec_rounds,
                         "spec_accepted_per_round": round(
                             len(req.tokens) / req.spec_rounds, 2)}
            tel.emit("serve_request", step=self.step_index,
                     tenant=req.tenant, status=status,
                     prompt_tokens=state.prompt_len,
                     tokens_out=len(req.tokens),
                     queue_ms=req.queue_ms, ttft_ms=req.ttft_ms,
                     tpot_ms=req.tpot_ms, e2e_ms=req.e2e_ms,
                     model_step=self.model_step, **extra)
        tracer = tracing.active()
        if tracer is not None:
            _ensure_request_trace(tracer, req)
            t_done_unix = _unix_at(req.t_done)
            tracer.emit_span(
                "serve.retire", t_done_unix, 0.0, step=self.step_index,
                parent_id=req.span_root, trace=req.trace,
                request_id=req.id, tenant=req.tenant, status=status,
                tokens_out=len(req.tokens))
            # The root span, submit..done: its children (queue wait,
            # reserve, prefill, decode lanes, swap pauses, retire) were
            # emitted live under the pre-allocated id.  When the request
            # arrived with wire trace context (X-DTF-Parent), the root
            # nests under the calling tier's span instead of floating —
            # that is what stitches the engine tree into the cross-tier
            # route.global -> route.cell -> route.fleet chain.
            tracer.emit_span(
                "serve.request", req.t_submit_unix,
                (req.t_done - req.t_submit) * 1e3, step=self.step_index,
                parent_id=req.wire_parent, span_id=req.span_root,
                trace=req.trace,
                request_id=req.id, tenant=req.tenant, status=status,
                tokens_out=len(req.tokens), queue_ms=req.queue_ms,
                ttft_ms=req.ttft_ms, tpot_ms=req.tpot_ms,
                model_step=self.model_step)
        return req

    # ------------------------------------------------------------- step

    def _spec_slots_active(self) -> bool:
        # Prefilling spec lanes don't draft yet — they are masked
        # passengers until their prompt K/V is resident.
        return any(s is not None and s.spec and not s.prefilling
                   for s in self._slots)

    def _advance_prefill(self) -> tuple[float, int]:
        """One chunk-prefill dispatch: every prefilling lane advances up
        to ``prefill_chunk`` prompt positions through the resident chunk
        program; lanes whose frontier reaches P-1 go live (real page
        table installed) and decode from the NEXT dispatch.  Non-
        prefilling rows ride along with sentinel tables — the program's
        shapes never depend on which lanes prefill, so it compiles once.

        Pad columns of a final partial chunk carry token 0 at positions
        >= the target: their junk K/V lands at positions the decode
        lane overwrites before its validity frontier reaches them (the
        same masking argument as rejected speculative writes).

        Returns (elapsed ms, prefilling rows advanced).
        """
        cfg = self.config
        jnp = self._jnp
        C = cfg.prefill_chunk
        B, MP = cfg.num_slots, cfg.max_pages_per_seq
        t0 = time.perf_counter()
        tokens = np.zeros((B, C), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.full((B, MP), cfg.num_pages, np.int32)
        rows: list[tuple[int, _Slot, int]] = []
        for slot, state in enumerate(self._slots):
            if (state is None or not state.prefilling
                    or state.request.abandoned):
                continue
            f = state.prefill_pos
            r = min(C, state.prefill_target - f)
            tokens[slot, :r] = state.request.prompt[f:f + r]
            positions[slot] = f
            tables[slot] = state.table
            rows.append((slot, state, r))
        if not rows:
            return 0.0, 0
        self.pools = self._chunk_prefill_fn(C)(
            self._tree, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(tables), self.pools)
        # Block here so the recorded chunk cost is device time, not
        # dispatch time — the decode step would otherwise absorb it and
        # the prefill_stall_ms decomposition would read zero.
        self._jax.block_until_ready(self.pools)
        dur_ms = (time.perf_counter() - t0) * 1e3
        self.prefill_ms_total += dur_ms
        tracer = tracing.active()
        now = time.perf_counter()
        for slot, state, r in rows:
            state.prefill_pos += r
            state.prefill_chunks += 1
            if state.prefilling:
                continue
            # Frontier reached P-1: install the real table — the lane
            # decodes like any other from the next dispatch on.
            self._tables[slot] = state.table
            req = state.request
            if tracer is not None:
                _ensure_request_trace(tracer, req)
                tracer.emit_span(
                    "serve.prefill", _unix_at(state.t_prefill_start),
                    (now - state.t_prefill_start) * 1e3,
                    step=self.step_index, parent_id=req.span_root,
                    trace=req.trace, request_id=req.id,
                    tenant=req.tenant, bucket=state.prefill_pages,
                    pages=state.prefill_pages,
                    prompt_tokens=state.prompt_len,
                    chunks=state.prefill_chunks, chunk_tokens=C)
        if self.telemetry is not None:
            self.telemetry.counter("serve_prefill_chunks").inc(len(rows))
            self.telemetry.histogram("serve_prefill_chunk_ms").record(
                dur_ms)
        return dur_ms, len(rows)

    def step(self, queue_depth: int = 0) -> list[Request]:
        """One decode step over the whole slot batch; returns the requests
        retired this step (completed/abandoned).  No-op (after adopting a
        staged swap) when every lane is idle.

        When at least one active lane opted into speculation the step
        runs the CHUNK program instead: speculative lanes feed their
        current token plus ``spec_k - 1`` drafts and may emit several
        tokens (the accepted prefix + the free correction), plain lanes
        ride the same dispatch and emit exactly their node-0 sample —
        token-for-token what the plain step would have produced."""
        self.apply_pending_swap()
        if self.active_slots == 0:
            return []
        jnp = self._jnp
        prefill_ms, prefill_rows = 0.0, 0
        if self.config.prefill_chunk:
            # Prompt chunks first, decode second: a lane whose frontier
            # reaches P-1 in this dispatch gets its real table installed
            # and its seed token rides the decode dispatch BELOW — its
            # first generated token costs no extra step.
            prefill_ms, prefill_rows = self._advance_prefill()
        spec_mode = (self._spec_step_fn is not None
                     and self._spec_slots_active())
        t0 = time.perf_counter()
        if spec_mode:
            K = self.config.spec_k
            chunk = np.zeros((self.config.num_slots, K), np.int32)
            chunk[:, 0] = self._tokens
            spec_rows = 0
            for slot, state in enumerate(self._slots):
                if state is not None and state.spec \
                        and not state.prefilling:
                    chunk[slot, 1:] = state.draft(K - 1)
                    spec_rows += 1
            greedy, sampled0, self.pools = self._spec_step_fn(
                self._tree, jnp.asarray(chunk),
                jnp.asarray(self._positions), jnp.asarray(self._tables),
                self.pools, jnp.asarray(self._temp),
                jnp.asarray(self._top_k), jnp.asarray(self._top_p),
                jnp.asarray(self._seeds))
            greedy, nxt = np.asarray(greedy), np.asarray(sampled0)
            self._spec_rows_last_step = spec_rows
        else:
            nxt, self.pools = self._step_fn(
                self._tree, jnp.asarray(self._tokens),
                jnp.asarray(self._positions), jnp.asarray(self._tables),
                self.pools, jnp.asarray(self._temp),
                jnp.asarray(self._top_k), jnp.asarray(self._top_p),
                jnp.asarray(self._seeds))
            nxt = np.asarray(nxt)
            self._spec_rows_last_step = 0
        now = time.perf_counter()
        step_ms = (now - t0) * 1e3
        self.step_index += 1
        tracer = tracing.active()
        round_id = 0
        t_round_unix = 0.0
        if tracer is not None:
            # One batched-round span per engine step; the live lanes fan
            # out below as children carrying their request's trace id, so
            # the same wall-clock interval appears once on the engine
            # timeline and once inside every participating request.
            t_round_unix = _unix_at(t0)
            round_id = tracer.emit_span(
                "serve.decode_round", t_round_unix, step_ms,
                step=self.step_index, parent_id=0,
                active_slots=self.active_slots,
                spec_rows=self._spec_rows_last_step,
                model_step=self.model_step)
        spec_accepted = 0
        retired: list[Request] = []
        for slot, state in enumerate(self._slots):
            if state is None:
                continue
            req = state.request
            if req.abandoned:
                retired.append(self._retire(slot, "abandoned"))
                continue
            if state.prefilling:
                # Masked passenger: no tokens this step (its decode-row
                # writes dropped through the sentinel table).
                continue
            if spec_mode and state.spec:
                # Longest drafted prefix matching the greedy argmaxes,
                # plus the free correction token — clamped to the lane's
                # remaining budget.
                row, g = chunk[slot], greedy[slot]
                accept = 1
                while (accept < K and row[accept] == g[accept - 1]
                       and not (req.eos_id is not None
                                and row[accept - 1] == req.eos_id)):
                    accept += 1
                accept = min(accept, state.budget - state.generated)
                emitted = [int(t) for t in row[1:accept]]
                emitted.append(int(g[accept - 1]))
                req.spec_rounds += 1
            else:
                emitted = [int(nxt[slot])]
            if req.t_first_token is None:
                req.t_first_token = now
            done_status = None
            count = 0
            for token in emitted:
                req.tokens.append(token)
                state.generated += 1
                count += 1
                if req.eos_id is not None and token == req.eos_id:
                    done_status = "ok"
                    break
                if state.generated >= state.budget:
                    done_status = "ok"
                    break
            if state.spec:
                state.commit(emitted[:count])
                # Count what actually LANDED — an accepted eos truncates
                # the emission mid-chunk, and the acceptance metric must
                # not report the tokens the break discarded.
                spec_accepted += count
            if tracer is not None:
                _ensure_request_trace(tracer, req)
                lane_attrs = {}
                if spec_mode and state.spec:
                    lane_attrs = {"accepted": count,
                                  "drafted": K - 1}
                tracer.emit_span(
                    "serve.decode_lane", t_round_unix, step_ms,
                    step=self.step_index, parent_id=round_id,
                    trace=req.trace, request_id=req.id,
                    tenant=req.tenant, tokens=count, **lane_attrs)
            if done_status is not None:
                retired.append(self._retire(slot, done_status))
            else:
                self._tokens[slot] = emitted[count - 1]
                self._positions[slot] += count
        self._spec_accepted_since_step = spec_accepted
        if self.telemetry is not None:
            tel = self.telemetry
            tel.histogram("serve_step_ms").record(step_ms)
            tel.gauge("serve_active_slots").set(self.active_slots)
            tel.gauge("serve_kv_pages_in_use").set(
                self.allocator.pages_in_use)
            tel.gauge("serve_kv_pages_peak").set(self.allocator.peak_in_use)
            tel.gauge("serve_kv_fragmentation").set(
                self.allocator.internal_fragmentation())
            # Resident compiled prefill programs (LRU-bounded) + the
            # chunk program(s): /statz and /metricz both surface this.
            tel.gauge("serve_compile_cache").set(
                len(self._prefill_fns) + len(self._chunk_fns))
            if spec_accepted:
                tel.counter("serve_spec_tokens").inc(spec_accepted)
            tel.emit("serve_step", step=self.step_index,
                     active_slots=self.active_slots + len(retired),
                     admitted=self._admitted_since_step,
                     retired=len(retired), queue_depth=queue_depth,
                     kv_pages_in_use=self.allocator.pages_in_use,
                     kv_pages_total=self.config.num_pages,
                     step_ms=round(step_ms, 3),
                     spec_rows=self._spec_rows_last_step,
                     spec_accepted=spec_accepted,
                     prefill_rows=prefill_rows,
                     prefill_ms=round(prefill_ms, 3),
                     model_step=self.model_step)
        self._admitted_since_step = 0
        return retired

    def fail_active(self, error: str) -> list[Request]:
        """Retire every live lane with an error (engine-fatal paths)."""
        out = []
        for slot, state in enumerate(self._slots):
            if state is None:
                continue
            state.request.error = error
            out.append(self._retire(slot, "error"))
        return out

    def stats(self) -> dict:
        """Occupancy/identity snapshot for /statz and the watch view."""
        return {
            "engine_step": self.step_index,
            "active_slots": self.active_slots,
            "num_slots": self.config.num_slots,
            "capacity_tokens": self.capacity,
            "model_step": self.model_step,
            "swaps": self.swaps,
            "quantize": self.config.quantize,
            "kv_dtype": self.config.kv_dtype,
            "spec_k": self.config.spec_k,
            "spec_rows": self._spec_rows_last_step,
            "prefill_chunk": self.config.prefill_chunk,
            "prefilling_slots": sum(
                1 for s in self._slots if s is not None and s.prefilling),
            # Resident compiled programs (the serve_compile_cache gauge's
            # /statz twin): per-bucket prefill programs are LRU-bounded
            # at prefill_cache_cap; chunk programs are one per width.
            "compile_cache": {
                "prefill_programs": len(self._prefill_fns),
                "chunk_programs": len(self._chunk_fns),
                "cap": self.config.prefill_cache_cap,
                "evictions": self._prefill_evictions,
            },
            "kv_pool": self.allocator.snapshot(),
        }
