"""Paged KV-cache page accounting — the serving tier's memory manager.

The engine's device memory for KV caches is ONE pool of fixed-size pages
per layer (``models/gpt.init_kv_pool``); every resident sequence draws
pages from it, so HBM is sized by *total resident tokens*, not by
``num_slots × max_len`` — the vLLM insight at the granularity this repo
needs.  This module owns the page bookkeeping on the host:

- :class:`PageAllocator` — free-list allocator with per-sequence page
  lists.  Allocation order is deterministic: never-used pages first
  (lowest index), then freed pages in FIFO order (oldest-freed reused
  first), so tests can pin the reuse/eviction order exactly.
- Reservations are worst-case at admission (``ceil((prompt + budget) /
  page_size)``): a sequence can never hit an out-of-pages condition
  mid-decode, so admission control is the ONLY backpressure point and
  in-flight streams never need mid-stream eviction.
- Internal fragmentation (the cost of fixed pages: the tail of the last
  page is reserved but may go unwritten) is reported per pool snapshot —
  the occupancy view the telemetry bus publishes every engine step.

Device tensors never live here: the allocator hands out page indices and
sentinel-padded page tables; :mod:`.engine` owns the arrays.
"""

from __future__ import annotations

import collections
import threading
from typing import Iterable

import numpy as np


class OutOfPages(RuntimeError):
    """The pool cannot cover a reservation — admission must wait/reject."""


class PageAllocator:
    """Host-side page bookkeeping for one paged KV pool.

    ``num_pages`` physical pages of ``page_size`` token slots each.  The
    sentinel index for "no page" in emitted page tables is ``num_pages``
    itself — out of bounds by exactly one, so the engine's scatters drop
    through it (``mode="drop"``) and gathers zero-fill (``mode="fill"``).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError(f"need positive pool geometry, got "
                             f"{num_pages} pages x {page_size} slots")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # Never-used pages dispense lowest-first; freed pages append to the
        # right and are reused oldest-freed-first once the fresh run is
        # exhausted (deterministic, testable reuse order).
        self._free: collections.deque[int] = collections.deque(
            range(num_pages))
        self._owned: dict[object, list[int]] = {}
        self._reserved_tokens: dict[object, int] = {}
        self._peak_in_use = 0      # occupancy high-water mark
        # The engine thread is the only mutator, but statz/healthz handler
        # threads read snapshot() concurrently — iterating
        # _reserved_tokens while free() pops a key is a RuntimeError.
        self._lock = threading.Lock()

    # ------------------------------------------------------------ state

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def peak_in_use(self) -> int:
        """High-water mark of :attr:`pages_in_use` since construction."""
        return self._peak_in_use

    @property
    def sequences(self) -> int:
        return len(self._owned)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` token slots."""
        return -(-int(tokens) // self.page_size)

    def utilization(self) -> float:
        """Fraction of the pool's pages currently reserved."""
        return self.pages_in_use / self.num_pages

    def internal_fragmentation(self) -> float:
        """Reserved-but-unrequested token slots / reserved slots — the
        fixed-page tax (0.0 when every reservation fills its last page,
        or when nothing is reserved)."""
        with self._lock:
            return self._fragmentation_locked()

    def _fragmentation_locked(self) -> float:
        reserved_slots = self.pages_in_use * self.page_size
        if not reserved_slots:
            return 0.0
        requested = sum(self._reserved_tokens.values())
        return (reserved_slots - requested) / reserved_slots

    def owned(self, seq_id) -> list[int]:
        """The sequence's pages in logical order (copy)."""
        return list(self._owned.get(seq_id, ()))

    # ------------------------------------------------------ alloc / free

    def can_alloc(self, tokens: int) -> bool:
        return self.pages_for(tokens) <= len(self._free)

    def alloc(self, seq_id, tokens: int) -> list[int]:
        """Reserve pages covering ``tokens`` token slots for ``seq_id``.

        Raises :class:`OutOfPages` without partial allocation when the
        pool cannot cover it, ``ValueError`` on double-alloc.
        """
        with self._lock:
            if seq_id in self._owned:
                raise ValueError(f"sequence {seq_id!r} already holds "
                                 "pages; use extend()")
            need = self.pages_for(tokens)
            if need > len(self._free):
                raise OutOfPages(
                    f"need {need} page(s) for {tokens} tokens, "
                    f"{len(self._free)} free of {self.num_pages}")
            pages = [self._free.popleft() for _ in range(need)]
            self._owned[seq_id] = pages
            self._reserved_tokens[seq_id] = int(tokens)
            self._peak_in_use = max(self._peak_in_use, self.pages_in_use)
            return list(pages)

    def extend(self, seq_id, tokens: int) -> list[int]:
        """Grow ``seq_id``'s reservation to cover ``tokens`` total token
        slots; returns the newly added pages (possibly empty).  Raises
        :class:`OutOfPages` leaving the existing reservation intact."""
        with self._lock:
            if seq_id not in self._owned:
                raise ValueError(f"sequence {seq_id!r} holds no pages")
            have = self._owned[seq_id]
            need = self.pages_for(tokens) - len(have)
            if need <= 0:
                self._reserved_tokens[seq_id] = max(
                    self._reserved_tokens[seq_id], int(tokens))
                return []
            if need > len(self._free):
                raise OutOfPages(
                    f"extend needs {need} page(s), {len(self._free)} free")
            fresh = [self._free.popleft() for _ in range(need)]
            have.extend(fresh)
            self._reserved_tokens[seq_id] = int(tokens)
            self._peak_in_use = max(self._peak_in_use, self.pages_in_use)
            return fresh

    def free(self, seq_id) -> int:
        """Return ``seq_id``'s pages to the pool (FIFO reuse order);
        returns how many were freed.  Freeing an unknown id is a no-op
        (retire paths may race a server shutdown)."""
        with self._lock:
            pages = self._owned.pop(seq_id, None)
            self._reserved_tokens.pop(seq_id, None)
            if not pages:
                return 0
            self._free.extend(pages)
            return len(pages)

    # ------------------------------------------------------- page tables

    def page_table(self, seq_id, max_pages: int) -> np.ndarray:
        """[max_pages] int32 physical-page row for the engine, padded with
        the OOB sentinel (``num_pages``)."""
        pages = self._owned.get(seq_id, ())
        if len(pages) > max_pages:
            raise ValueError(
                f"sequence {seq_id!r} holds {len(pages)} pages > "
                f"max_pages={max_pages}")
        row = np.full((max_pages,), self.num_pages, np.int32)
        row[:len(pages)] = pages
        return row

    @staticmethod
    def empty_table(num_pages: int, max_pages: int) -> np.ndarray:
        """All-sentinel row — an idle slot's page table."""
        return np.full((max_pages,), num_pages, np.int32)

    def snapshot(self) -> dict:
        """Occupancy view for telemetry/statz (handler-thread safe)."""
        with self._lock:
            return {
                "num_pages": self.num_pages,
                "page_size": self.page_size,
                "pages_in_use": self.pages_in_use,
                "peak_in_use": self._peak_in_use,
                "free_pages": self.free_pages,
                "sequences": self.sequences,
                "utilization": round(self.utilization(), 4),
                "internal_fragmentation": round(
                    self._fragmentation_locked(), 4),
            }


def reservation_tokens(prompt_len: int, num_tokens: int) -> int:
    """Worst-case token slots a request can touch: the prompt plus its
    full generation budget (positions ``0 .. prompt+budget-1``)."""
    return int(prompt_len) + int(num_tokens)
