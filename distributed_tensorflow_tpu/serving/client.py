"""Thin serving client — the wire format of docs/serving.md as methods.

Stdlib-only (urllib over HTTP/1.1) so any process in the repo — tests,
bench legs, ci.sh snippets — can drive a serving process without extra
dependencies.  Errors map back from status codes:
:class:`Backpressure` (429), :class:`Overloaded` (503), ``ValueError``
(400), ``RuntimeError`` (500/other).

Connection-level failures (refused/reset — the target process is gone or
restarting, nothing was served) are retried with bounded exponential
backoff before surfacing as a typed :class:`ReplicaUnavailable`; a fleet
frontend (``serving/router.py``) failing over, or a replica respawning
behind it, is therefore invisible to a caller that rides out the backoff
window instead of seeing a raw socket error.  Timeouts are deliberately
NOT retried: a request that timed out mid-flight may still be executing,
and resending it is the caller's decision, not the transport's.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

from ..utils import tracing


class Backpressure(RuntimeError):
    """HTTP 429: the tenant's queue is at its bound — retry with backoff."""


class Overloaded(RuntimeError):
    """HTTP 503: the request waited past the server's timeout."""


class ReplicaUnavailable(RuntimeError):
    """No TCP conversation at all (connection refused/reset, retries
    exhausted): the serving process is dead or still booting.  A router
    treats this as "fail over to another replica"; a direct caller as
    "the server is down"."""


class ServeClient:
    """``ServeClient("http://127.0.0.1:8700").generate([1,2,3], 8)``.

    ``retries``/``backoff_s`` bound the connection-failure retry loop
    (``retries=0`` disables it — the router's forwarding path does this
    so ITS failover logic, not the transport, owns the retry decision).
    """

    def __init__(self, base_url: str, timeout_s: float = 180.0, *,
                 retries: int = 3, backoff_s: float = 0.1):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)

    def _request(self, path: str, payload: dict | None = None,
                 headers: dict[str, str] | None = None) -> dict:
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data,
            headers={"Content-Type": "application/json", **(headers or {})})
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                try:
                    detail = json.loads(e.read()).get("error", "")
                except Exception:
                    detail = ""
                if e.code == 429:
                    raise Backpressure(detail or "queue full") from None
                if e.code == 503:
                    raise Overloaded(detail or "overloaded") from None
                if e.code == 400:
                    raise ValueError(detail or "bad request") from None
                raise RuntimeError(f"HTTP {e.code}: {detail}") from None
            except (urllib.error.URLError, ConnectionError) as e:
                reason = getattr(e, "reason", e)
                if isinstance(reason, TimeoutError) and not isinstance(
                        reason, ConnectionError):
                    # The server may still be working on the request —
                    # never auto-resend past a timeout.
                    raise
                if attempt < self.retries:
                    time.sleep(delay)
                    delay *= 2
                    continue
                raise ReplicaUnavailable(
                    f"{self.base_url}: {reason}") from None
        raise AssertionError("unreachable")  # loop always returns/raises

    def generate(self, prompt: list[int], num_tokens: int = 16, *,
                 tenant: str = "default", eos_id: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 speculative: bool = False, trace: str | None = None,
                 trace_parent: int = 0,
                 trace_sampled: bool = False) -> dict:
        """Returns the server's response dict (``tokens`` holds
        prompt + generation; latency fields ride along).
        ``speculative`` opts into the server's paged speculative arm
        (greedy-only; same tokens either way).  ``trace`` attaches
        cross-tier trace context as ``X-DTF-*`` headers (mint one with
        :func:`utils.tracing.mint_trace` or pass an upstream context
        through); every serving tier forwards it, so the whole stack's
        spans land in ONE trace."""
        headers = (tracing.wire_headers(trace, trace_parent, trace_sampled)
                   if trace is not None else None)
        return self._request("/generate", {
            "prompt": list(prompt), "num_tokens": num_tokens,
            "tenant": tenant, "eos_id": eos_id,
            "temperature": temperature, "top_k": top_k, "top_p": top_p,
            "seed": seed, "speculative": speculative}, headers=headers)

    def health(self) -> dict:
        return self._request("/healthz")

    def stats(self) -> dict:
        return self._request("/statz")

    def fleetz(self) -> dict:
        """The fleet membership view (router processes only): router
        stats + every member's identity, state, and last /statz
        snapshot — ``watch_serve --fleet``'s feed."""
        return self._request("/fleetz")

    def cellz(self) -> dict:
        """The cell membership view (global-router processes only,
        ``serving/cells.py``): global stats + every cell's identity,
        state, tenant homes, and last fleet-router snapshot —
        ``watch_serve --cells``'s feed."""
        return self._request("/cellz")
