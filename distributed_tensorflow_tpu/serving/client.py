"""Thin serving client — the wire format of docs/serving.md as methods.

Stdlib-only (urllib over HTTP/1.1) so any process in the repo — tests,
bench legs, ci.sh snippets — can drive a serving process without extra
dependencies.  Errors map back from status codes:
:class:`Backpressure` (429), :class:`Overloaded` (503), ``ValueError``
(400), ``RuntimeError`` (500/other).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request


class Backpressure(RuntimeError):
    """HTTP 429: the tenant's queue is at its bound — retry with backoff."""


class Overloaded(RuntimeError):
    """HTTP 503: the request waited past the server's timeout."""


class ServeClient:
    """``ServeClient("http://127.0.0.1:8700").generate([1,2,3], 8)``."""

    def __init__(self, base_url: str, timeout_s: float = 180.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def _request(self, path: str, payload: dict | None = None) -> dict:
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                detail = ""
            if e.code == 429:
                raise Backpressure(detail or "queue full") from None
            if e.code == 503:
                raise Overloaded(detail or "overloaded") from None
            if e.code == 400:
                raise ValueError(detail or "bad request") from None
            raise RuntimeError(f"HTTP {e.code}: {detail}") from None

    def generate(self, prompt: list[int], num_tokens: int = 16, *,
                 tenant: str = "default", eos_id: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 speculative: bool = False) -> dict:
        """Returns the server's response dict (``tokens`` holds
        prompt + generation; latency fields ride along).
        ``speculative`` opts into the server's paged speculative arm
        (greedy-only; same tokens either way)."""
        return self._request("/generate", {
            "prompt": list(prompt), "num_tokens": num_tokens,
            "tenant": tenant, "eos_id": eos_id,
            "temperature": temperature, "top_k": top_k, "top_p": top_p,
            "seed": seed, "speculative": speculative})

    def health(self) -> dict:
        return self._request("/healthz")

    def stats(self) -> dict:
        return self._request("/statz")
