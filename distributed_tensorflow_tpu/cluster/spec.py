"""Cluster spec & process bring-up (C4/C5, N1) — TPU pod-slice flavor.

The reference parses ``ps_hosts``/``worker_hosts`` into a ``tf.train.ClusterSpec``
and starts an in-process gRPC server (reference ``distributed.py:49-57``).  On
TPU there is no parameter server and no per-tensor gRPC transport: each
TPU-VM host runs one identical process, bulk data rides ICI collectives, and
only a thin control plane (discovery/barrier/health) crosses DCN.

:class:`ClusterSpec` keeps the same construction API so launch scripts port
unchanged; ``job_name='ps'`` is accepted and mapped onto the coordination
service role (the closest capability: a process that serves control-plane
state and blocks in ``join()``, ``distributed.py:55-56``).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ClusterSpec:
    """Named job → host list mapping (API parity with ``tf.train.ClusterSpec``)."""

    jobs: dict[str, list[str]] = field(default_factory=dict)

    def __init__(self, jobs: dict[str, list[str] | str]):
        parsed = {}
        for name, hosts in jobs.items():
            if isinstance(hosts, str):
                hosts = [h for h in hosts.split(",") if h]
            parsed[name] = list(hosts)
        self.jobs = parsed

    def job_tasks(self, job_name: str) -> list[str]:
        return self.jobs.get(job_name, [])

    def num_tasks(self, job_name: str) -> int:
        return len(self.jobs.get(job_name, []))

    @property
    def num_workers(self) -> int:
        # Reference: num_workers = len(worker_spec) (distributed.py:52).
        return self.num_tasks("worker")

    def task_address(self, job_name: str, task_index: int) -> str:
        tasks = self.job_tasks(job_name)
        if not 0 <= task_index < len(tasks):
            raise ValueError(f"task_index {task_index} out of range for job "
                             f"{job_name!r} with {len(tasks)} tasks")
        return tasks[task_index]

    @property
    def coordinator_address(self) -> str:
        """Control-plane address: first 'ps' host if present, else worker 0's
        host at port+1000.

        This is how the reference's PS address is reinterpreted: the host that
        used to own the parameters now merely hosts the coordination service.
        The port offset in the no-PS topology avoids colliding with worker 0's
        own port, which ``jax.distributed.initialize`` binds as its coordinator.
        """
        for job in ("ps", "coordinator"):
            tasks = self.job_tasks(job)
            if tasks:
                return tasks[0]
        host, port = self.task_address("worker", 0).rsplit(":", 1)
        return f"{host}:{int(port) + 1000}"


def is_chief(task_index: int) -> bool:
    """Chief election, reference semantics: task 0 (``distributed.py:58``)."""
    return task_index == 0
