"""Process bring-up — the ``tf.train.Server`` equivalent (N1 control plane).

Reference behavior being matched (``distributed.py:54-56,125``): constructing a
server starts the distributed runtime for this process; PS processes park in
``join()``; workers hand ``server.target`` to the session layer.

TPU-native: the data plane needs no server at all (XLA collectives over ICI are
compiled into the step), so what remains is the control plane — multi-host
process group formation (``jax.distributed``) plus the framework's own C++
coordination service (discovery, barrier, health, restart detection) layered
on DCN.  See :mod:`.coordination` for the native service.
"""

from __future__ import annotations

import os

import jax

from .spec import ClusterSpec, is_chief


class TpuServer:
    """One per process.  Forms the multi-host process group and exposes the
    control-plane handle the supervisor layer uses.
    """

    def __init__(self, cluster: ClusterSpec, job_name: str, task_index: int, *,
                 initialize_distributed: bool | None = None,
                 coord_service: bool = True,
                 heartbeat_timeout: float = 10.0,
                 kv_persist_path: str | None = None,
                 coord_instances: int = 1,
                 coord_standbys: str | None = None):
        self.cluster = cluster
        self.job_name = job_name
        self.task_index = task_index
        self.is_chief = is_chief(task_index) and job_name == "worker"
        self._coord_server = None
        self._coord_extra_servers: list = []
        self._coord_client = None
        if coord_instances < 1:
            raise ValueError(
                f"coord_instances must be >= 1, got {coord_instances}")

        num_workers = cluster.num_workers
        if initialize_distributed is None:
            # Multi-process JAX init only when there really are multiple worker
            # hosts; single-host (the common TPU pod-slice-per-host case and
            # all tests) needs none.
            initialize_distributed = num_workers > 1 and job_name == "worker" \
                and os.environ.get("DTF_TPU_DISABLE_JAX_DISTRIBUTED", "0") != "1"
        if initialize_distributed:
            jax.distributed.initialize(
                coordinator_address=cluster.task_address("worker", 0),
                num_processes=num_workers,
                process_id=task_index,
            )

        if coord_service:
            from . import coordination
            addr = cluster.coordinator_address
            host, port = addr.rsplit(":", 1)
            if job_name == "ps" or (job_name == "worker" and self.is_chief
                                    and not cluster.job_tasks("ps")):
                # The process at the coordination address hosts the service —
                # the PS role's surviving responsibility.  With
                # coord_instances > 1 it hosts the whole sharded plane:
                # instance i on port+i carrying shard identity (i, N),
                # instance 0 the control shard (docs/param_exchange.md,
                # "Hierarchical exchange").
                for i in range(coord_instances):
                    srv = coordination.CoordinationServer(
                        port=int(port) + i, num_tasks=max(num_workers, 1),
                        heartbeat_timeout=heartbeat_timeout,
                        persist_path=(f"{kv_persist_path}.shard{i}"
                                      if kv_persist_path and i else
                                      kv_persist_path),
                        shard=i, nshards=coord_instances)
                    srv.start()
                    if i == 0:
                        self._coord_server = srv
                    else:
                        self._coord_extra_servers.append(srv)
            if job_name == "worker":
                # Coordinator / KV-shard HA (docs/fault_tolerance.md):
                # coord_standbys wires ordered warm-standby endpoint
                # lists — a plain "h:p,..." list for the control shard,
                # or a per-instance map "0:h:p;1:h:p" covering every KV
                # shard of a sharded plane.  Each instance's client walks
                # its list on a dead or demoted primary, so a coordinator
                # SIGKILL is a lease-bounded stall, not an outage.
                standby_map = coordination.parse_standby_map(coord_standbys)
                if coord_instances > 1:
                    spec = ",".join(f"{host}:{int(port) + i}"
                                    for i in range(coord_instances))
                    self._coord_client = coordination.CoordinationRouter(
                        spec, task_id=task_index, standbys=standby_map)
                else:
                    self._coord_client = coordination.CoordinationClient(
                        host, int(port), task_id=task_index,
                        standbys=standby_map.get(0))

    @property
    def target(self) -> str:
        """Session-layer handle (parity with ``server.target``, ``distributed.py:125``)."""
        return f"dtf-tpu://{self.cluster.coordinator_address}"

    @property
    def coordination_client(self):
        return self._coord_client

    def join(self) -> None:
        """Block forever serving the control plane (PS parity, ``distributed.py:55-56``)."""
        if self._coord_server is not None:
            self._coord_server.join()
        else:  # pragma: no cover - degenerate config
            import threading
            threading.Event().wait()

    def shutdown(self) -> None:
        if self._coord_client is not None:
            # Voluntary departure: LEAVE shrinks the elastic membership set
            # immediately (epoch bump, no lease wait), so peers still
            # running never stall on a worker that already finished or is
            # being preempted.  Best-effort — a dead coordinator must not
            # block shutdown (leave() swallows coordination errors).
            self._coord_client.leave()
            self._coord_client.close()
            self._coord_client = None
        for srv in self._coord_extra_servers:
            srv.stop()
        self._coord_extra_servers = []
        if self._coord_server is not None:
            self._coord_server.stop()
            self._coord_server = None
