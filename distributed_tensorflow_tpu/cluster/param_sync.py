"""Cross-process asynchronous parameter averaging over the control plane.

The reference's async mode is Hogwild through the parameter server: every
worker pushes and pulls at its own cadence, and the parameters survive
worker death on the PS (reference ``distributed.py:102``; SURVEY N2/N4).
TPU-natively the data plane moved into HBM + ICI collectives — but ICI
collectives are lockstep.  For *independent-cadence* async across worker
processes, this module re-creates the PS exchange at the control plane:

- each worker periodically publishes its (locally merged) parameters to the
  coordination service's KV store and averages in whatever peers have
  published — no barrier, bounded staleness, workers never wait on each
  other (the reference's stale-update semantics, without the races);
- published parameters survive on the service across worker restarts (and —
  with the coordinator's KV journal — across coordinator restarts too), so a
  rejoining worker pulls the collective's current state — the PS-durability
  role the reference relied on.

Size: two transports, chosen per publication by payload size:

- **KV chunks** (small models, no shared-FS assumption): zlib-compressed
  float32, base64, chunked across KV entries with a meta entry written last
  as the commit point — model size bounded by coordinator memory, not the
  wire protocol's request-line cap.
- **Logdir binary side-channel** (``exchange_dir`` set and raw bytes ≥
  ``binary_threshold``): the flat float32 buffer is written to a
  sequence-numbered file in the shared run directory (the same shared-FS
  assumption checkpoints already make), committed by a KV pointer entry
  (``v2bin``) carrying length + CRC.  The coordinator socket then moves a
  ~60-byte pointer instead of gigabytes of base64 — this is what lets a
  100M+-parameter transformer exchange at disk bandwidth, matching the
  reference PS which moved full models every step (``distributed.py:145``).

Either way a torn read (meta/chunk/file mismatch while a peer republishes)
fails the checksum and that peer is skipped for the round; binary files are
sequence-numbered so a writer never truncates a file a reader may hold open.
"""

from __future__ import annotations

import base64
import os
import zlib
from typing import Any

import jax
import numpy as np

KEY_FORMAT = "dtf/async_params/{}/task{}"
# Chunk size in base64 chars: comfortably under the coordinator's 8 MiB
# request-line cap and the client's initial response buffer.
CHUNK_CHARS = 512 * 1024
# Raw float32 bytes at which publications switch to the binary side-channel
# (when the averager has an exchange_dir): past this, base64-through-one-
# socket is the bottleneck, not the model math.
BINARY_THRESHOLD_BYTES = 8 << 20


def _flatten(params: Any) -> np.ndarray:
    leaves = [np.asarray(l, np.float32).ravel()
              for l in jax.tree.leaves(params)]
    return (np.ascontiguousarray(np.concatenate(leaves))
            if leaves else np.zeros((0,), np.float32))


def _unflatten(flat: np.ndarray, template: Any) -> Any | None:
    leaves, treedef = jax.tree.flatten(template)
    total = sum(int(np.prod(l.shape)) for l in leaves)
    if flat.size != total:
        return None  # peer published a different model/shape — skip it
    out, pos = [], 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[pos:pos + n].reshape(l.shape))
        pos += n
    return jax.tree.unflatten(treedef, out)


def _encode_flat(flat: np.ndarray) -> str:
    return base64.b64encode(zlib.compress(flat.tobytes(), level=1)).decode()


def _encode(params: Any) -> str:
    return _encode_flat(_flatten(params))


def _decode(value: str, template: Any) -> Any | None:
    try:
        raw = zlib.decompress(base64.b64decode(value))
    except Exception:
        return None
    return _unflatten(np.frombuffer(raw, np.float32), template)


def publish_chunked(coord, base_key: str, payload: str,
                    chunk_chars: int = CHUNK_CHARS) -> int:
    """Write ``payload`` as ``<base>.c<i>`` chunks, then the ``<base>`` meta
    entry (``v1 <nchunks> <len> <crc32>``) as the commit point.  Returns the
    chunk count."""
    nchunks = max(1, -(-len(payload) // chunk_chars))
    for i in range(nchunks):
        coord.kv_set(f"{base_key}.c{i}",
                     payload[i * chunk_chars:(i + 1) * chunk_chars])
    crc = zlib.crc32(payload.encode())
    coord.kv_set(base_key, f"v1 {nchunks} {len(payload)} {crc:08x}")
    return nchunks


def fetch_chunked(coord, base_key: str, meta: str | None = None
                  ) -> str | None:
    """Read a chunked payload; None when absent or torn (checksum/length
    mismatch against the meta entry).  ``meta``: the already-fetched meta
    entry, to save the extra coordinator round-trip."""
    if meta is None:
        meta = coord.kv_get(base_key)
    if meta is None:
        return None
    parts = meta.split()
    if len(parts) != 4 or parts[0] != "v1":
        return None
    try:
        nchunks, total, crc = int(parts[1]), int(parts[2]), int(parts[3], 16)
    except ValueError:
        return None
    chunks = []
    for i in range(nchunks):
        chunk = coord.kv_get(f"{base_key}.c{i}")
        if chunk is None:
            return None
        chunks.append(chunk)
    payload = "".join(chunks)
    if len(payload) != total or zlib.crc32(payload.encode()) != crc:
        return None
    return payload


def publish_binary(coord, base_key: str, flat: np.ndarray, exchange_dir: str,
                   task: int, seq: int) -> str:
    """Write ``flat`` to ``<exchange_dir>/task{task}.{seq}.bin`` (atomic
    tmp+rename, fsynced) and KV-commit a ``v2bin`` pointer with length +
    CRC.  Returns the file name.  Files older than ``seq - 1`` for this
    task are garbage-collected — a reader holding the previous sequence's
    pointer can still finish its read."""
    os.makedirs(exchange_dir, exist_ok=True)
    fname = f"task{task}.{seq}.bin"
    tmp = os.path.join(exchange_dir, fname + ".tmp")
    with open(tmp, "wb") as fh:
        flat.tofile(fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(exchange_dir, fname))
    crc = zlib.crc32(flat.data)
    coord.kv_set(base_key, f"v2bin {fname} {flat.nbytes} {crc:08x} {seq}")
    for old in os.listdir(exchange_dir):
        if not old.startswith(f"task{task}."):
            continue
        try:
            old_seq = int(old.split(".")[1])
        except (IndexError, ValueError):
            continue
        if old_seq <= seq - 2:
            try:
                os.unlink(os.path.join(exchange_dir, old))
            except OSError:
                pass
    return fname


def fetch_binary(meta: str, exchange_dir: str) -> np.ndarray | None:
    """Resolve a ``v2bin`` pointer to its flat float32 buffer; None when
    the file is missing/torn (length or CRC mismatch)."""
    parts = meta.split()
    if len(parts) != 5 or parts[0] != "v2bin":
        return None
    fname, nbytes, crc_hex = parts[1], parts[2], parts[3]
    if os.sep in fname or fname.startswith("."):
        return None  # pointer must stay inside the exchange dir
    path = os.path.join(exchange_dir, fname)
    try:
        flat = np.fromfile(path, np.float32)
    except OSError:
        return None
    try:
        if flat.nbytes != int(nbytes) or zlib.crc32(flat.data) != int(
                crc_hex, 16):
            return None
    except ValueError:
        return None
    return flat


class ParamAverager:
    """Publish/average merged parameters through the coordination KV.

    ``namespace`` scopes the KV keys to one run (callers pass a digest of
    the run's logdir): a restarted worker of the SAME run rejoins its
    collective, while a fresh run against a still-running coordination
    service never adopts a dead run's weights.

    ``exchange_dir`` (usually ``<logdir>/async_exchange``) enables the
    binary side-channel for payloads of at least ``binary_threshold`` raw
    bytes; without it every publication rides the KV.  Readers handle both
    formats regardless — the WRITER's size decides the transport.
    """

    def __init__(self, coord, task_index: int, num_workers: int,
                 namespace: str = "default",
                 exchange_dir: str | None = None,
                 binary_threshold: int = BINARY_THRESHOLD_BYTES):
        self._coord = coord
        self._task = task_index
        self._num_workers = num_workers
        self._ns = namespace
        self._dir = exchange_dir
        self._threshold = binary_threshold
        # Resume the sequence from files a previous incarnation left behind:
        # a restart starting over at 0 would strand the old high-sequence
        # files (2x model size each) outside GC's reach for ~500 periods.
        self._seq = 0
        if exchange_dir is not None and os.path.isdir(exchange_dir):
            prefix = f"task{task_index}."
            for f in os.listdir(exchange_dir):
                if f.startswith(prefix) and f.endswith(".bin"):
                    try:
                        self._seq = max(self._seq, int(f.split(".")[1]))
                    except (IndexError, ValueError):
                        pass
        #: transport and MB/s of the last publish (observability/bench)
        self.last_publish_transport = ""
        self.last_publish_mb_per_sec = 0.0

    def _key(self, task: int) -> str:
        return KEY_FORMAT.format(self._ns, task)

    def _publish(self, host_merged: Any) -> None:
        import time
        flat = _flatten(host_merged)
        t0 = time.perf_counter()
        if self._dir is not None and flat.nbytes >= self._threshold:
            self._seq += 1
            publish_binary(self._coord, self._key(self._task), flat,
                           self._dir, self._task, self._seq)
            self.last_publish_transport = "binary"
        else:
            publish_chunked(self._coord, self._key(self._task),
                            _encode_flat(flat))
            self.last_publish_transport = "kv"
        dt = time.perf_counter() - t0
        self.last_publish_mb_per_sec = (flat.nbytes / 1e6 / dt) if dt else 0.0

    def _fetch_peer(self, task: int, template: Any) -> Any | None:
        meta = self._coord.kv_get(self._key(task))
        if meta is None:
            return None
        if meta.startswith("v2bin"):
            if self._dir is None:
                return None
            flat = fetch_binary(meta, self._dir)
            return None if flat is None else _unflatten(flat, template)
        value = fetch_chunked(self._coord, self._key(task), meta=meta)
        return None if value is None else _decode(value, template)

    def exchange(self, merged: Any, alive=None) -> tuple[Any, int]:
        """Publish ``merged`` (host-side average of local replicas), pull
        live peers' publications, and return
        ``(averaged_params, num_peers_included)``.

        Peers that haven't published yet (slower cadence, just restarted)
        are simply absent — nobody blocks; that IS the async contract.
        ``alive`` (per-task liveness bits from the heartbeat health cache)
        excludes dead/finished peers, whose frozen snapshots would otherwise
        anchor the average forever.
        """
        host_merged = jax.tree.map(lambda x: np.asarray(x, np.float32), merged)
        self._publish(host_merged)
        contributions = [host_merged]
        for task in range(self._num_workers):
            if task == self._task:
                continue
            if alive is not None and task < len(alive) and not alive[task]:
                continue
            peer = self._fetch_peer(task, host_merged)
            if peer is not None:
                contributions.append(peer)
        n = len(contributions)
        if n == 1:
            return merged, 0
        avg = jax.tree.map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *contributions)
        return avg, n - 1

    def pull_latest(self, template: Any) -> Any | None:
        """Average of everything published in this run's namespace
        (restart-and-rejoin: a rejoining worker adopts the collective's
        state instead of step 1 — stale entries are exactly the durability
        this provides, so liveness is deliberately NOT checked here)."""
        contributions = []
        for task in range(self._num_workers):
            peer = self._fetch_peer(task, template)
            if peer is not None:
                contributions.append(peer)
        if not contributions:
            return None
        return jax.tree.map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *contributions)


def run_namespace(logdir: str) -> str:
    """Stable per-run KV namespace: a digest of the run's logdir (shared by
    all of the run's workers and its restarts; different for fresh runs)."""
    import os
    import zlib as _zlib
    return format(_zlib.crc32(os.path.abspath(logdir).encode()), "08x")
