"""Cross-process asynchronous parameter averaging over the control plane.

The reference's async mode is Hogwild through the parameter server: every
worker pushes and pulls at its own cadence, and the parameters survive
worker death on the PS (reference ``distributed.py:102``; SURVEY N2/N4).
TPU-natively the data plane moved into HBM + ICI collectives — but ICI
collectives are lockstep.  For *independent-cadence* async across worker
processes, this module re-creates the PS exchange at the control plane:

- each worker periodically publishes its (locally merged) parameters to the
  coordination service's KV store and averages in whatever peers have
  published — no barrier, bounded staleness, workers never wait on each
  other (the reference's stale-update semantics, without the races);
- published parameters survive on the service across worker restarts (and —
  with the coordinator's KV journal — across coordinator restarts too), so a
  rejoining worker pulls the collective's current state — the PS-durability
  role the reference relied on.

Payloads travel in the parameters' OWN dtype: a bf16 model moves half the
bytes a float32 encoding would (the r3 float32 pin doubled every bf16
exchange), and averaging upcasts to float32 per leaf before casting back.
The wire format is the concatenation of each leaf's native bytes; the
READER's template supplies dtypes/shapes.  Structural mismatches (a peer
running a different model or dtype — including same-byte-length
collisions) are detected via a per-publication ``tree_fingerprint``
carried on a ``<key>.fp`` side entry: the first mismatch logs one loud
ERROR naming the peer, after which the peer is skipped quietly until its
fingerprint matches again.  Payloads from pre-fingerprint publishers
(no ``.fp`` entry) fall back to the byte-length check alone.

Size: two transports, chosen per publication by payload size:

- **KV chunks** (small models, no shared-FS assumption): zlib-compressed
  native bytes, base64, chunked across KV entries with a meta entry written
  last as the commit point — model size bounded by coordinator memory, not
  the wire protocol's request-line cap.
- **Logdir binary side-channel** (``exchange_dir`` set and raw bytes ≥
  ``binary_threshold``): the flat native-dtype buffer is written to a
  sequence-numbered file in the shared run directory (the same shared-FS
  assumption checkpoints already make), committed by a KV pointer entry
  (``v2bin``) carrying length + CRC.  The coordinator socket then moves a
  ~60-byte pointer instead of gigabytes of base64 — this is what lets a
  100M+-parameter transformer exchange at disk bandwidth, matching the
  reference PS which moved full models every step (``distributed.py:145``).

Either way a torn read (meta/chunk/file mismatch while a peer republishes)
fails the checksum and that peer is skipped for the round; binary files are
sequence-numbered so a writer never truncates a file a reader may hold
open, and the last ``BINARY_GC_KEEP`` sequences are retained so a reader
whose pointer-fetch-to-file-read gap spans publish periods still finds its
file.  Skipped peers are counted (``fetch_skips``) and logged, so silent
participation loss is visible in worker output.

Traffic: the full-state exchange above moves O(N·P) native-dtype bytes
per period per worker.  :class:`CompressedShardedAverager` replaces the
steady state with a three-stage compressed, sharded protocol — delta
encoding against an agreed consensus, error-feedback int8/bf16
quantization with per-block scales (EQuARX, arXiv:2506.17615), and a
reduce-scatter of the flat buffer across the active membership (Xu et
al., arXiv:2004.13336) — cutting the wire to O(2·P/N) quantized bytes,
with the full-state path retained as the bootstrap fallback and the
periodic anchor.  docs/param_exchange.md specifies the wire format.
"""

from __future__ import annotations

import base64
import contextlib
import os
import struct
import time
import zlib
from typing import Any

import jax
import numpy as np

from ..parallel.sync import (contiguous_shard_bounds, slice_exporters,
                             slice_of_task, slice_topology)
from ..utils import faults, tracing

KEY_FORMAT = "dtf/async_params/{}/task{}"
# Chunk size in base64 chars: comfortably under the coordinator's 8 MiB
# request-line cap and the client's initial response buffer.
CHUNK_CHARS = 512 * 1024
# Raw bytes at which publications switch to the binary side-channel (when
# the averager has an exchange_dir): past this, base64-through-one-socket
# is the bottleneck, not the model math.
BINARY_THRESHOLD_BYTES = 8 << 20
# Sequences of a task's binary files kept on disk; older ones are GC'd at
# publish time.  3 (current + two predecessors) tolerates a reader whose
# kv_get-to-read gap spans two publish periods on a slow shared FS.
BINARY_GC_KEEP = 3


def _leaf_meta(leaf) -> tuple[np.dtype, tuple, int]:
    """(dtype, shape, nbytes) without materializing device leaves."""
    dt = np.dtype(leaf.dtype) if hasattr(leaf, "dtype") else np.dtype(
        type(leaf))
    shape = tuple(getattr(leaf, "shape", ()))
    n = 1
    for s in shape:
        n *= int(s)
    return dt, shape, n * dt.itemsize


def tree_fingerprint(params: Any) -> str:
    """8-hex digest of the tree's per-leaf (dtype, shape) sequence.

    Carried in the publication meta so a peer running a different model or
    dtype (e.g. a mixed-version worker still publishing float32 of a bf16
    model) is diagnosed with one clear error instead of being silently
    byte-length-skipped every round (ADVICE r4).
    """
    metas = "|".join(f"{dt.str}{shape}"
                     for dt, shape, _ in map(_leaf_meta,
                                             jax.tree.leaves(params)))
    return format(zlib.crc32(metas.encode()), "08x")


def _flatten(params: Any) -> np.ndarray:
    """Concatenated native-dtype bytes of the tree's leaves (uint8)."""
    leaves = [np.ascontiguousarray(np.asarray(l))
              for l in jax.tree.leaves(params)]
    if not leaves:
        return np.zeros((0,), np.uint8)
    bufs = [l.reshape(-1).view(np.uint8) for l in leaves]
    if len(bufs) == 1:
        return bufs[0]  # GB-scale single-leaf trees skip the concat copy
    return np.concatenate(bufs)


def _unflatten(buf: np.ndarray, template: Any) -> Any | None:
    """Rebuild a tree shaped/typed like ``template`` from native bytes;
    None when the byte length doesn't match (peer published a different
    model/dtype — skip it)."""
    leaves, treedef = jax.tree.flatten(template)
    metas = [_leaf_meta(l) for l in leaves]
    if buf.nbytes != sum(m[2] for m in metas):
        return None
    out, pos = [], 0
    for dt, shape, nb in metas:
        out.append(buf[pos:pos + nb].view(dt).reshape(shape))
        pos += nb
    return jax.tree.unflatten(treedef, out)


def _encode_flat(flat: np.ndarray) -> str:
    # zlib/base64 accept the array's buffer directly: no .tobytes() copy of
    # the whole flat tree before compression (at GB scale that copy was a
    # second full-size host buffer on the hot path).
    return base64.b64encode(zlib.compress(flat.data, 1)).decode()


def _encode(params: Any) -> str:
    return _encode_flat(_flatten(params))


def _decode(value: str, template: Any) -> Any | None:
    try:
        raw = zlib.decompress(base64.b64decode(value))
    except Exception:
        return None
    return _unflatten(np.frombuffer(raw, np.uint8), template)


def _mean_leaves(*xs):
    """Average in float32, return in the leaves' own dtype.  Accumulates
    in place (one f32 buffer) rather than stacking — at GB-scale trees a
    stack of N f32 upcasts would multiply peak host memory by N."""
    dt = xs[0].dtype
    acc = np.array(xs[0], np.float32)  # always a fresh buffer
    for x in xs[1:]:
        # Buffered mixed-dtype add: the ufunc streams the bf16->f32 cast
        # through cache-sized chunks instead of materializing another
        # full-size f32 temp per peer (~2x faster and allocation-stable
        # at GB-scale trees).
        np.add(acc, x, out=acc)
    acc /= len(xs)
    return acc.astype(dt)


def publish_chunked(coord, base_key: str, payload: str,
                    chunk_chars: int = CHUNK_CHARS, fp: str = "") -> int:
    """Write ``payload`` as ``<base>.c<i>`` chunks, then the ``<base>`` meta
    entry (``v1 <nchunks> <len> <crc32>``) as the commit point.  Returns the
    chunk count.

    ``fp`` (the publisher's ``tree_fingerprint``) rides a SEPARATE
    ``<base>.fp`` key, written before the meta commit point, NOT appended
    to the meta line: readers that predate the fingerprint parse the meta
    with strict field counts, and extending it would make every new
    publication unreadable to them — the rolling-upgrade scenario the
    fingerprint exists to diagnose."""
    nchunks = max(1, -(-len(payload) // chunk_chars))
    for i in range(nchunks):
        coord.kv_set(f"{base_key}.c{i}",
                     payload[i * chunk_chars:(i + 1) * chunk_chars])
    # Unconditional (empty fp clears a predecessor's entry): a stale .fp
    # left behind by an upgraded incarnation would otherwise permanently
    # exclude a downgraded-but-matching publisher.
    coord.kv_set(f"{base_key}.fp", fp)
    crc = zlib.crc32(payload.encode())
    coord.kv_set(base_key, f"v1 {nchunks} {len(payload)} {crc:08x}")
    return nchunks


def fetch_chunked(coord, base_key: str, meta: str | None = None
                  ) -> str | None:
    """Read a chunked payload; None when absent or torn (checksum/length
    mismatch against the meta entry).  ``meta``: the already-fetched meta
    entry, to save the extra coordinator round-trip."""
    if meta is None:
        meta = coord.kv_get(base_key)
    if meta is None:
        return None
    parts = meta.split()
    if len(parts) != 4 or parts[0] != "v1":
        return None
    try:
        nchunks, total, crc = int(parts[1]), int(parts[2]), int(parts[3], 16)
    except ValueError:
        return None
    chunks = []
    for i in range(nchunks):
        chunk = coord.kv_get(f"{base_key}.c{i}")
        if chunk is None:
            return None
        chunks.append(chunk)
    payload = "".join(chunks)
    if len(payload) != total or zlib.crc32(payload.encode()) != crc:
        return None
    return payload


def publish_binary(coord, base_key: str, flat: np.ndarray, exchange_dir: str,
                   task: int, seq: int,
                   gc_keep: int = BINARY_GC_KEEP, fp: str = "") -> str:
    """Write ``flat`` (native-dtype bytes, uint8) to
    ``<exchange_dir>/task{task}.{seq}.bin`` (atomic tmp+rename) and
    KV-commit a ``v2bin`` pointer with length + CRC (``fp`` rides the
    side ``<base>.fp`` key — see ``publish_chunked``).  Returns the file
    name.  The newest ``gc_keep`` sequences for this task survive; older
    files are garbage-collected — a reader holding a recent pointer can
    still finish its read even if it lags a couple of publish periods."""
    os.makedirs(exchange_dir, exist_ok=True)
    fname = f"task{task}.{seq}.bin"
    tmp = os.path.join(exchange_dir, fname + ".tmp")
    # No fsync: publications are throwaway state, not checkpoints.  The
    # close() below is what shared filesystems key visibility on
    # (close-to-open consistency), and the KV pointer's CRC rejects a
    # file whose data never survived a host crash — the reader skips that
    # peer for a round, which is this module's documented degradation
    # mode anyway.  An fsync here would serialize every publish on disk
    # bandwidth (~13 s/GB on a commodity disk) for durability nobody uses.
    with open(tmp, "wb") as fh:
        flat.tofile(fh)
    os.replace(tmp, os.path.join(exchange_dir, fname))
    coord.kv_set(f"{base_key}.fp", fp)  # unconditional — see publish_chunked
    crc = zlib.crc32(flat.data)
    coord.kv_set(base_key, f"v2bin {fname} {flat.nbytes} {crc:08x} {seq}")
    for old in os.listdir(exchange_dir):
        if not old.startswith(f"task{task}."):
            continue
        try:
            old_seq = int(old.split(".")[1])
        except (IndexError, ValueError):
            continue
        if old_seq <= seq - gc_keep:
            try:
                os.unlink(os.path.join(exchange_dir, old))
            except OSError:
                pass
    return fname


def fetch_binary(meta: str, exchange_dir: str) -> np.ndarray | None:
    """Resolve a ``v2bin`` pointer to its flat byte buffer (uint8); None
    when the file is missing/torn (length or CRC mismatch)."""
    parts = meta.split()
    if len(parts) != 5 or parts[0] != "v2bin":
        return None
    fname, nbytes, crc_hex = parts[1], parts[2], parts[3]
    if os.sep in fname or fname.startswith("."):
        return None  # pointer must stay inside the exchange dir
    path = os.path.join(exchange_dir, fname)
    try:
        flat = np.fromfile(path, np.uint8)
    except OSError:
        return None
    try:
        if flat.nbytes != int(nbytes) or zlib.crc32(flat.data) != int(
                crc_hex, 16):
            return None
    except ValueError:
        return None
    return flat


class ParamAverager:
    """Publish/average merged parameters through the coordination KV.

    ``namespace`` scopes the KV keys to one run (callers pass a digest of
    the run's logdir): a restarted worker of the SAME run rejoins its
    collective, while a fresh run against a still-running coordination
    service never adopts a dead run's weights.

    ``exchange_dir`` (usually ``<logdir>/async_exchange``) enables the
    binary side-channel for payloads of at least ``binary_threshold`` raw
    bytes; without it every publication rides the KV.  Readers handle both
    formats regardless — the WRITER's size decides the transport.

    Parameters keep their dtype end to end: a bf16 tree publishes bf16
    bytes (half the float32 volume) and the averaged result comes back
    bf16, with the mean computed in float32 per leaf.
    """

    def __init__(self, coord, task_index: int, num_workers: int,
                 namespace: str = "default",
                 exchange_dir: str | None = None,
                 binary_threshold: int = BINARY_THRESHOLD_BYTES,
                 print_fn=print):
        self._coord = coord
        self._task = task_index
        self._num_workers = num_workers
        self._ns = namespace
        self._dir = exchange_dir
        self._threshold = binary_threshold
        self._print = print_fn
        # Resume the sequence from files a previous incarnation left behind:
        # a restart starting over at 0 would strand the old high-sequence
        # files (model-size each) outside GC's reach for ~500 periods.
        self._seq = 0
        if exchange_dir is not None and os.path.isdir(exchange_dir):
            prefix = f"task{task_index}."
            for f in os.listdir(exchange_dir):
                if not f.startswith(prefix):
                    continue
                try:
                    if f.endswith(".bin"):
                        self._seq = max(self._seq, int(f.split(".")[1]))
                    elif f.endswith(".blob"):
                        # task<t>.<tag>.<seq>.blob (compressed exchange)
                        self._seq = max(self._seq,
                                        int(f.rsplit(".", 2)[1]))
                except (IndexError, ValueError):
                    pass
        #: transport and MB/s of the last publish (observability/bench)
        self.last_publish_transport = ""
        self.last_publish_mb_per_sec = 0.0
        #: bytes-on-wire accounting (docs/param_exchange.md): payload bytes
        #: this worker moved in its last exchange (out = published, in =
        #: fetched) and cumulatively — the quantity the compressed protocol
        #: exists to shrink and the bench/CI gate assert on.
        self.last_bytes_out = 0
        self.last_bytes_in = 0
        self.total_bytes_out = 0
        self.total_bytes_in = 0
        #: intra-slice (ICI/shared-memory-class) bytes of the last
        #: exchange — hierarchical mode only; NEVER part of the inter-host
        #: wire accounting above (docs/param_exchange.md, "Hierarchical
        #: exchange").
        self.last_intra_bytes = 0
        self.total_intra_bytes = 0
        self._wire_scope = "inter"
        #: full-state-equivalent bytes / bytes-on-wire of the last exchange
        #: (1.0-ish for the uncompressed path; >= 4 is the compressed
        #: protocol's acceptance bar).  None before the first exchange.
        self.last_ratio: float | None = None
        self._telemetry = None
        # One-shot extra fields for the next telemetry record (the
        # compressed subclass tags its full-state fallbacks this way
        # without emitting a second record).
        self._note_extra: dict[str, Any] = {}
        #: per-peer count of rounds skipped on a torn/missing payload —
        #: persistent skipping (ADVICE r3) shows up here and in the log
        self.fetch_skips: dict[int, int] = {}
        # Peers already diagnosed with a tree-fingerprint mismatch: the
        # structural error prints ONCE per peer (it will never heal on its
        # own), then the peer is skipped quietly.
        self._fp_mismatch_reported: set[int] = set()

    def _key(self, task: int) -> str:
        return KEY_FORMAT.format(self._ns, task)

    def attach_telemetry(self, telemetry) -> None:
        """Route per-exchange observability (``kind="param_exchange"``
        records, ``exchange_bytes``/``exchange_ratio`` gauges) through the
        run's telemetry bus (docs/param_exchange.md)."""
        self._telemetry = telemetry

    def _count_wire(self, direction: str, nbytes: int) -> None:
        if self._wire_scope == "intra":
            # Intra-slice hop of the hierarchical exchange: ICI-class
            # traffic, accounted apart from the inter-host wire bytes the
            # compressed protocol exists to shrink.
            self.last_intra_bytes += nbytes
            self.total_intra_bytes += nbytes
            return
        if direction == "out":
            self.last_bytes_out += nbytes
            self.total_bytes_out += nbytes
        else:
            self.last_bytes_in += nbytes
            self.total_bytes_in += nbytes

    @contextlib.contextmanager
    def _intra(self):
        """Route :meth:`_count_wire` to the intra-slice books inside."""
        prev, self._wire_scope = self._wire_scope, "intra"
        try:
            yield
        finally:
            self._wire_scope = prev

    def _note_exchange(self, *, peers: int, native_bytes: int,
                       compressed: bool, dur_ms: float,
                       **fields: Any) -> None:
        """Per-exchange accounting + telemetry record.  ``native_bytes`` is
        the tree's size in its own dtype; ``full_state_bytes`` is what the
        UNCOMPRESSED full-state exchange would have moved this period on
        the same transport — (1 publish + ``peers`` fetches) of the native
        bytes, with the KV path's base64 framing included so compressed
        and full-state wire bytes compare like for like."""
        wire = self.last_bytes_out + self.last_bytes_in
        if self._dir is not None and native_bytes >= self._threshold:
            unit = native_bytes            # binary side-channel: raw bytes
        else:
            unit = (native_bytes * 4 + 2) // 3   # KV: base64 chars
        full = unit * (1 + max(peers, 0))
        self.last_ratio = (full / wire) if wire else None
        extra, self._note_extra = self._note_extra, {}
        fields = {**extra, **fields}
        tel = self._telemetry
        if tel is None:
            return
        tel.gauge("exchange_bytes").set(wire)
        if self.last_ratio is not None:
            tel.gauge("exchange_ratio").set(round(self.last_ratio, 3))
        if fields.get("hierarchical"):
            # Hierarchical mode: surface the inter-host share and the
            # slice id live (the training loop folds these gauges into
            # the STATPUT summary, so tools/watch_run.py can flag a
            # worker silently falling back to the flat exchange).
            tel.gauge("exchange_inter_bytes").set(
                fields.get("inter_bytes", wire))
            if fields.get("slice") is not None:
                tel.gauge("exchange_slice").set(fields["slice"])
        else:
            # Flat/fallback period: CLEAR the placement gauges (-1 = the
            # "absent" sentinel the loop filters on).  Leaving the last
            # hierarchical values in place would keep stamping a stale
            # slice id into the live stats, and watch_run's flat-fallback
            # detector — which keys on the slice being ABSENT — could
            # never fire for exactly the worker it exists to catch.
            tel.gauge("exchange_inter_bytes").set(-1)
            tel.gauge("exchange_slice").set(-1)
        tel.counter("exchange_bytes_total").inc(wire)
        tel.histogram("exchange_ms").record(dur_ms)
        tel.emit("param_exchange", step=0, peers=peers,
                 bytes_out=self.last_bytes_out, bytes_in=self.last_bytes_in,
                 bytes_on_wire=wire, full_state_bytes=full,
                 ratio=(round(self.last_ratio, 3)
                        if self.last_ratio is not None else None),
                 compressed=compressed, dur_ms=round(dur_ms, 3), **fields)

    def _publish(self, host_merged: Any, fp: str | None = None) -> None:
        flat = _flatten(host_merged)
        if fp is None:
            fp = tree_fingerprint(host_merged)
        t0 = time.perf_counter()
        if self._dir is not None and flat.nbytes >= self._threshold:
            self._seq += 1
            publish_binary(self._coord, self._key(self._task), flat,
                           self._dir, self._task, self._seq, fp=fp)
            self.last_publish_transport = "binary"
            self._count_wire("out", flat.nbytes)
        else:
            payload = _encode_flat(flat)
            publish_chunked(self._coord, self._key(self._task), payload,
                            fp=fp)
            self.last_publish_transport = "kv"
            self._count_wire("out", len(payload))
        dt = time.perf_counter() - t0
        self.last_publish_mb_per_sec = (flat.nbytes / 1e6 / dt) if dt else 0.0

    def _fetch_peer(self, task: int, template: Any,
                    my_fp: str | None = None) -> Any | None:
        meta = self._coord.kv_get(self._key(task))
        if meta is None:
            return None  # peer hasn't published yet — normal, not a skip
        peer_fp = self._coord.kv_get(self._key(task) + ".fp")
        if peer_fp:  # empty/absent -> pre-fingerprint publisher, no check
            mine = my_fp if my_fp is not None else tree_fingerprint(template)
            if peer_fp != mine:
                # Structural mismatch (different model or dtype on the
                # wire): a torn read heals next round, this doesn't — say
                # so loudly ONCE per mismatch episode, then skip quietly.
                if task not in self._fp_mismatch_reported:
                    self._fp_mismatch_reported.add(task)
                    self._print(
                        f"[param_sync] ERROR: peer {task} publishes a "
                        f"different parameter tree (fingerprint {peer_fp} "
                        f"vs local {mine}) — mixed model/dtype versions in "
                        f"one run; this peer will be excluded from "
                        f"averaging until it matches")
                self.fetch_skips[task] = self.fetch_skips.get(task, 0) + 1
                return None
            # Healed (restarted with the right model): arm the one-time
            # error again so a LATER mismatch is a new loud episode.
            self._fp_mismatch_reported.discard(task)
        if meta.startswith("v2bin"):
            if self._dir is None:
                peer = None
            else:
                flat = fetch_binary(meta, self._dir)
                if flat is not None:
                    self._count_wire("in", flat.nbytes)
                peer = None if flat is None else _unflatten(flat, template)
        else:
            value = fetch_chunked(self._coord, self._key(task), meta=meta)
            if value is not None:
                self._count_wire("in", len(value))
            peer = None if value is None else _decode(value, template)
        if peer is None:
            # Published but unreadable (torn mid-republish, GC'd file,
            # shape/dtype mismatch): count and say so — persistent skipping
            # quietly shrinks averaging participation otherwise.
            n = self.fetch_skips.get(task, 0) + 1
            self.fetch_skips[task] = n
            self._print(f"[param_sync] task {self._task}: skipping peer "
                        f"{task} this round (unreadable payload, "
                        f"{n} skips total)")
        return peer

    def exchange(self, merged: Any, alive=None) -> tuple[Any, int]:
        """Publish ``merged`` (host-side average of local replicas), pull
        live peers' publications, and return
        ``(averaged_params, num_peers_included)``.

        Peers that haven't published yet (slower cadence, just restarted)
        are simply absent — nobody blocks; that IS the async contract.
        ``alive`` (per-task liveness bits from the heartbeat health cache)
        excludes dead/finished peers, whose frozen snapshots would otherwise
        anchor the average forever.
        """
        t0 = time.perf_counter()
        self.last_bytes_out = self.last_bytes_in = 0
        self.last_intra_bytes = 0
        host_merged = jax.tree.map(
            lambda x: np.ascontiguousarray(np.asarray(x)), merged)
        my_fp = tree_fingerprint(host_merged)
        self._publish(host_merged, fp=my_fp)
        contributions = [host_merged]
        for task in range(self._num_workers):
            if task == self._task:
                continue
            if alive is not None and task < len(alive) and not alive[task]:
                continue
            peer = self._fetch_peer(task, host_merged, my_fp=my_fp)
            if peer is not None:
                contributions.append(peer)
        n = len(contributions)
        native_bytes = sum(m[2] for m in map(_leaf_meta,
                                             jax.tree.leaves(host_merged)))
        self._note_exchange(peers=n - 1, native_bytes=native_bytes,
                            compressed=False,
                            dur_ms=(time.perf_counter() - t0) * 1000.0,
                            transport=self.last_publish_transport)
        if n == 1:
            return merged, 0
        avg = jax.tree.map(_mean_leaves, *contributions)
        return avg, n - 1

    def pull_latest(self, template: Any) -> Any | None:
        """Average of everything published in this run's namespace
        (restart-and-rejoin: a rejoining worker adopts the collective's
        state instead of step 1 — stale entries are exactly the durability
        this provides, so liveness is deliberately NOT checked here)."""
        my_fp = tree_fingerprint(template)
        contributions = []
        for task in range(self._num_workers):
            peer = self._fetch_peer(task, template, my_fp=my_fp)
            if peer is not None:
                contributions.append(peer)
        if not contributions:
            return None
        return jax.tree.map(_mean_leaves, *contributions)


# =====================================================================
# Compressed sharded exchange: delta encoding + error-feedback
# quantization + reduce-scatter over the KV plane (docs/param_exchange.md)
# =====================================================================
#
# The full-state exchange above moves O(N * P) native-dtype bytes per
# period per worker.  The compressed protocol replaces it with three
# stages, cutting the wire to O(2 * P / N) quantized bytes:
#
# 1. **delta** — each worker publishes its parameters as a delta against
#    the last agreed consensus (EQuARX-style per-block-scaled int8, or
#    bf16), with its own quantization error fed back into the next delta
#    through a residual accumulator (error feedback: compression error is
#    retransmitted, never compounded);
# 2. **sharded reduce** — the flat buffer is partitioned into
#    ``len(active)`` contiguous shards keyed off the membership epoch
#    (``parallel.sync.contiguous_shard_bounds``); the owner of shard j
#    (``active[j]``) fetches only shard j of each peer's delta, averages,
#    and publishes ONE frozen reduced record per (epoch, round, shard);
# 3. **assemble** — every worker rebuilds the next consensus from the N
#    frozen reduced shards (identical bytes for every reader, so the
#    consensus chain never diverges), applying it one period stale as a
#    delta correction — the same delayed-averaging math OverlappedAverager
#    already pins.
#
# Full-state records remain the FALLBACK (bootstrap, non-float trees,
# evicted self) and the periodic ANCHOR: the anchor chief (lowest active
# task) publishes the raw-f32 consensus every ``anchor_every`` rounds and
# on every membership-epoch change, so rejoining/elastic workers always
# have an exact bootstrap point and laggards resynchronize.

#: Self-describing blob header: every anchor/delta/reduced record starts
#: with these 12 little-endian u32 fields, so integrity/round/epoch checks
#: never depend on cross-key atomicity in the KV.
BLOB_HEADER = struct.Struct("<12I")
BLOB_MAGIC = 0x44544651  # "DTFQ"
# Version 2 (r13): contributor-mask bits became POSITIONS in the exchange
# group instead of raw task ids (see ``contributor_bit``).  The bump makes
# records from a pre-r13 worker (elastic rejoin on an old build) fail the
# structural check and fall into the existing skip paths, instead of its
# id-keyed mask bits being silently misread as positional — which could
# fake an "included" bit and drop a peer's progress without re-injection.
BLOB_VERSION = 2
KIND_ANCHOR, KIND_DELTA, KIND_REDUCED, KIND_CAST = 1, 2, 3, 4
FMT_RAW_F32, FMT_INT8, FMT_BF16 = 0, 1, 2
#: Per-block scale granularity of the int8 quantizer (elements/block).
DEFAULT_QUANT_BLOCK = 1024
#: Full-state anchor cadence (rounds) — bootstrap/resync points.
DEFAULT_ANCHOR_EVERY = 8
#: Streaming chunk for the blob file writer/reader (compress into the
#: file in pieces; never materialize a second full-size host buffer).
BLOB_IO_CHUNK = 4 << 20

DELTA_KEY = "dtf/async_delta/{}/task{}/s{}"
REDUCED_KEY = "dtf/async_reduced/{}/s{}"
ANCHOR_KEY = "dtf/async_anchor/{}"
# Hierarchical exchange (docs/param_exchange.md, "Hierarchical
# exchange"): a slice member's raw intra-slice delta, and the exporter's
# assembled-consensus broadcast back into the slice.  Both are
# ICI/shared-memory-class traffic — never quantized, never counted as
# inter-host wire bytes.
MEMBER_DELTA_KEY = "dtf/async_member/{}/g{}/task{}"
CAST_KEY = "dtf/async_cast/{}/g{}"
# Per-task tree fingerprint (compressed path): blob headers carry only
# element counts, and a mixed-version peer can match counts with a
# different leaf layout — which would corrupt the shared consensus
# silently.  The same once-loudly-then-skip rule as the legacy path.
FP_KEY = "dtf/async_fp/{}/task{}"


def _float_dtype(dt) -> bool:
    dt = np.dtype(dt)
    return dt.kind == "f" or dt.name == "bfloat16"


def contributor_bit(group, task: int) -> int:
    """Contributor-mask bit for ``task`` within its exchange ``group``.

    Bits are POSITIONS in the group's (sorted) member ordering, not raw
    task ids: the u32 mask then covers any <=32-member group whatever the
    ids — which is what lets the hierarchical inter-slice level carry
    exporter task ids from fleets of hundreds (32 slices x 32 members =
    1024 workers) without the flat protocol's id<32 restriction.  Every
    worker derives the same group from the membership epoch, so every
    side computes the same bit."""
    group = tuple(group)
    try:
        idx = group.index(task)
    except ValueError:
        # A task outside its group would alias another member's
        # positional bit — the mask would fake an "included" bit for a
        # DIFFERENT peer and its exclusion re-injection would silently
        # never fire.  That is a caller bug; refuse loudly (the same
        # id-vs-position confusion the BLOB_VERSION=2 bump rejects on
        # the wire).
        raise ValueError(
            f"task {task} is not a member of exchange group {group}")
    return 1 << min(idx, 31)


def _flatten_f32(tree: Any) -> np.ndarray:
    """Concatenated float32 view of a (float-leaved) tree's values."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate([np.asarray(l).astype(np.float32,
                                               copy=False).reshape(-1)
                           for l in leaves])


def _unflatten_f32(vec: np.ndarray, template: Any) -> Any:
    """Rebuild a tree shaped/dtyped like ``template`` from a float32
    value vector (each leaf cast back to its own dtype)."""
    leaves, treedef = jax.tree.flatten(template)
    out, pos = [], 0
    for leaf in leaves:
        a = np.asarray(leaf)
        out.append(vec[pos:pos + a.size].astype(a.dtype).reshape(a.shape))
        pos += a.size
    return jax.tree.unflatten(treedef, out)


def quantize_int8(values: np.ndarray, block: int
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Per-block absmax int8 quantization: ``values`` (float32 ``[n]``) ->
    ``(scales float32 [ceil(n/block)], q int8 [n])`` with
    ``dequant = q * scale_of_block``.  An all-zero block keeps scale 1.0
    (its codes are zero anyway) so dequantization never divides by zero."""
    n = values.size
    if n == 0:
        return np.zeros((0,), np.float32), np.zeros((0,), np.int8)
    nblocks = -(-n // block)
    pad = nblocks * block - n
    v = np.pad(values, (0, pad)) if pad else values
    vb = v.reshape(nblocks, block)
    scales = np.abs(vb).max(axis=1).astype(np.float32) / 127.0
    scales[scales == 0.0] = 1.0
    q = np.rint(vb / scales[:, None]).clip(-127, 127).astype(np.int8)
    return scales, q.reshape(-1)[:n]


def dequantize_int8(scales: np.ndarray, q: np.ndarray,
                    block: int) -> np.ndarray:
    n = q.size
    if n == 0:
        return np.zeros((0,), np.float32)
    pad = scales.size * block - n
    qq = np.pad(q, (0, pad)) if pad else q
    out = qq.reshape(scales.size, block).astype(np.float32) * scales[:, None]
    return out.reshape(-1)[:n]


def encode_shard(values: np.ndarray, *, kind: int, fmt: int, round_: int,
                 epoch: int, shard: int, nshards: int, mask: int,
                 block: int) -> list:
    """Encode a float32 value vector as a self-describing blob: the
    48-byte header, then the format's payload (int8: the per-block f32
    scale array then the codes; bf16: the cast values; raw: exact f32).
    Returns a list of buffers (header, parts...) so large payloads stream
    into the writer without a concat copy."""
    header = BLOB_HEADER.pack(BLOB_MAGIC, BLOB_VERSION, kind, fmt,
                              round_ & 0xFFFFFFFF, epoch & 0xFFFFFFFF,
                              shard, nshards, mask & 0xFFFFFFFF,
                              block, values.size, 0)
    if fmt == FMT_RAW_F32:
        return [header, np.ascontiguousarray(values, np.float32).data]
    if fmt == FMT_INT8:
        scales, q = quantize_int8(values, block)
        return [header, scales.data, q.data]
    if fmt == FMT_BF16:
        import ml_dtypes
        bf = np.ascontiguousarray(values.astype(ml_dtypes.bfloat16))
        # uint8 view: the buffer protocol has no bf16 format character.
        return [header, bf.view(np.uint8).data]
    raise ValueError(f"unknown shard format {fmt}")


def dequantize_parts(parts: list, fmt: int, block: int) -> np.ndarray:
    """The float32 values a reader of ``encode_shard``'s blob will decode,
    computed straight from the encoded buffers — no full-size join copy
    and no header re-parse.  The error-feedback residual needs the exact
    post-quantization values on every publish, so this sits on the hot
    path (bit-identical to ``decode_shard``: both read the same scale and
    code bytes)."""
    if fmt == FMT_RAW_F32:
        return np.frombuffer(parts[1], "<f4")
    if fmt == FMT_INT8:
        scales = np.frombuffer(parts[1], "<f4")
        q = np.frombuffer(parts[2], np.int8)
        return dequantize_int8(scales, q, block)
    if fmt == FMT_BF16:
        import ml_dtypes
        return np.frombuffer(parts[1], ml_dtypes.bfloat16
                             ).astype(np.float32)
    raise ValueError(f"unknown shard format {fmt}")


def decode_shard(blob: bytes) -> tuple[dict, np.ndarray] | None:
    """Parse a blob back into ``(header_fields, float32 values)``; None on
    any structural problem (wrong magic/version, truncated payload)."""
    if blob is None or len(blob) < BLOB_HEADER.size:
        return None
    (magic, version, kind, fmt, round_, epoch, shard, nshards, mask,
     block, n, _reserved) = BLOB_HEADER.unpack_from(blob)
    if magic != BLOB_MAGIC or version != BLOB_VERSION:
        return None
    body = memoryview(blob)[BLOB_HEADER.size:]
    try:
        if fmt == FMT_RAW_F32:
            vals = np.frombuffer(body, "<f4", count=n).copy()
        elif fmt == FMT_INT8:
            if n and block < 1:
                return None  # malformed header, not a crash
            nblocks = -(-n // block) if n else 0
            scales = np.frombuffer(body, "<f4", count=nblocks)
            q = np.frombuffer(body, np.int8, count=n, offset=nblocks * 4)
            vals = dequantize_int8(scales, q, block)
        elif fmt == FMT_BF16:
            import ml_dtypes
            vals = np.frombuffer(body, ml_dtypes.bfloat16,
                                 count=n).astype(np.float32)
        else:
            return None
    except ValueError:
        return None  # truncated payload
    header = {"kind": kind, "fmt": fmt, "round": round_, "epoch": epoch,
              "shard": shard, "nshards": nshards, "mask": mask,
              "block": block, "n_values": n}
    return header, vals


def write_blob_file(exchange_dir: str, tag: str, seq: int, parts: list,
                    compress: bool = True,
                    chunk: int = BLOB_IO_CHUNK) -> tuple[str, int, int]:
    """Stream ``parts`` (buffers) into ``<dir>/<tag>.<seq>.blob``
    (atomic tmp+rename), compressing chunk-wise INTO the file writer when
    ``compress`` — the payload is never materialized a second time on the
    host, whatever its size.  Returns ``(fname, file_bytes, crc32)`` where
    the CRC covers the file bytes as written (what a reader must verify
    BEFORE decoding)."""
    os.makedirs(exchange_dir, exist_ok=True)
    fname = f"{tag}.{seq}.blob"
    tmp = os.path.join(exchange_dir, fname + ".tmp")
    crc = 0
    written = 0
    # No fsync, same contract as publish_binary: publications are
    # throwaway state; the CRC in the pointer rejects a crash-torn file.
    with open(tmp, "wb") as fh:
        compressor = zlib.compressobj(1) if compress else None

        def emit(piece: bytes):
            nonlocal crc, written
            if piece:
                fh.write(piece)
                crc = zlib.crc32(piece, crc)
                written += len(piece)

        for part in parts:
            mv = memoryview(part).cast("B")
            for off in range(0, len(mv), chunk):
                piece = mv[off:off + chunk]
                emit(compressor.compress(piece) if compressor else piece)
        if compressor is not None:
            emit(compressor.flush())
    os.replace(tmp, os.path.join(exchange_dir, fname))
    return fname, written, crc


def read_blob_file(exchange_dir: str, fname: str, raw_len: int,
                   file_len: int, crc: int, compressed: bool,
                   chunk: int = BLOB_IO_CHUNK) -> bytes | None:
    """Resolve a ``v3blob`` pointer: verify length + CRC of the file bytes
    while streaming them (decompressing chunk-wise into the preallocated
    output), None when missing/torn."""
    if os.sep in fname or fname.startswith("."):
        return None  # pointer must stay inside the exchange dir
    path = os.path.join(exchange_dir, fname)
    out = bytearray(raw_len)
    pos = 0
    seen_crc = 0
    seen_len = 0
    decompressor = zlib.decompressobj() if compressed else None
    try:
        with open(path, "rb") as fh:
            while True:
                piece = fh.read(chunk)
                if not piece:
                    break
                seen_crc = zlib.crc32(piece, seen_crc)
                seen_len += len(piece)
                raw = decompressor.decompress(piece) if decompressor \
                    else piece
                if pos + len(raw) > raw_len:
                    return None
                out[pos:pos + len(raw)] = raw
                pos += len(raw)
    except (OSError, zlib.error):
        return None
    if seen_len != file_len or seen_crc != crc or pos != raw_len:
        return None
    return bytes(out)


class CompressedShardedAverager(ParamAverager):
    """Delta + error-feedback-quantized + sharded parameter exchange.

    Drop-in for :class:`ParamAverager` (same ``exchange``/``pull_latest``
    contract, same transports, wrappable by :class:`OverlappedAverager`),
    but the steady-state wire traffic is the quantized DELTA reduced
    across ``len(active)`` shards instead of N full-precision mirrors —
    see the protocol comment above and docs/param_exchange.md for the
    wire format.

    ``quant``: ``"int8"`` (per-block absmax scales, ``block`` elements
    per scale) or ``"bf16"``.  ``anchor_every``: full-state anchor
    cadence in consensus rounds.  ``epoch_fn`` supplies the membership
    view ``() -> (epoch, active_task_ids)`` (e.g. from
    ``CoordinationClient.members``); shard ownership is keyed ONLY on it,
    never on per-worker health views, so every worker derives the same
    owner map.  Without one, the membership is static (epoch 0, all
    tasks).

    Consistency invariant: reduced records are written ONCE per
    ``(epoch, round, shard)`` by the shard's owner, so every worker
    assembling round k reads identical bytes and the consensus chain is
    exact across the fleet.  A worker whose delta missed a frozen reduce
    re-injects that shard's transmitted values into its residual — its
    progress rides the next round instead of being lost.

    Host memory: three extra float32 model-size buffers (consensus,
    residual, snapshot) beyond the base class.
    """

    #: Largest exchange group the u32 contributor bitmask can name.  The
    #: flat protocol's group is the whole worker set; the hierarchical
    #: subclass exchanges over groups of <= 32 at each LEVEL (32 slices x
    #: 32 members) and raises its own ceiling accordingly.
    MAX_GROUP = 32

    def __init__(self, coord, task_index: int, num_workers: int,
                 namespace: str = "default",
                 exchange_dir: str | None = None,
                 binary_threshold: int = BINARY_THRESHOLD_BYTES,
                 print_fn=print, quant: str = "int8",
                 block: int = DEFAULT_QUANT_BLOCK,
                 anchor_every: int = DEFAULT_ANCHOR_EVERY,
                 epoch_fn=None):
        super().__init__(coord, task_index, num_workers, namespace=namespace,
                         exchange_dir=exchange_dir,
                         binary_threshold=binary_threshold,
                         print_fn=print_fn)
        if quant not in ("int8", "bf16"):
            raise ValueError(f"quant must be 'int8' or 'bf16', got {quant!r}")
        if num_workers > self.MAX_GROUP:
            # The contributor bitmask is a u32 header field; past 32 tasks
            # per exchange group the excluded-delta detection would
            # silently false-negative and drop training progress.  Refuse
            # loudly instead.
            raise ValueError(
                f"compressed sharded exchange supports at most "
                f"{self.MAX_GROUP} workers (contributor bitmask), got "
                f"{num_workers}; use the hierarchical exchange "
                f"(--slice_size) or the full-state exchange "
                f"(--async_compress=off)")
        self._fmt = FMT_INT8 if quant == "int8" else FMT_BF16
        self._block = max(int(block), 1)
        self._anchor_every = max(int(anchor_every), 1)
        self._epoch_fn = epoch_fn
        # Consensus chain state.
        self._consensus: np.ndarray | None = None  # f32 [n]
        self._residual: np.ndarray | None = None   # f32 [n] error feedback
        self._snap: np.ndarray | None = None       # base of my last delta
        self._k = 0                                # consensus round
        self._epoch = -1
        self._active: tuple[int, ...] = tuple(range(num_workers))
        self._pending_reduce: int | None = None
        self._published_round: int | None = None
        self._reduced_done: set[tuple[int, int, int]] = set()
        self._my_reduced: dict[tuple[int, int, int], np.ndarray] = {}
        # Fetched-record caches: delta/reduced records are immutable per
        # (epoch, round, shard) once written, so a round assembled over
        # several periods (peers on slower cadences) fetches each record
        # ONCE — retries cost nothing on the wire.
        self._peer_reduced: dict[tuple[int, int, int],
                                 tuple[np.ndarray, int]] = {}
        self._my_delta: tuple[int, np.ndarray] | None = None
        # Structural-safety state (FP_KEY): my cached fingerprint, whether
        # it is on the KV yet, and the per-peer fingerprints read so far.
        self._fp: str | None = None
        self._fp_published = False
        self._peer_fp: dict[int, str] = {}
        self._warned_nonfloat = False
        #: residual RMS after the last delta publish (telemetry; the
        #: error-feedback health signal — it should stay bounded).
        self.last_residual_rms = 0.0
        #: consensus rounds completed (bench/observability).
        self.rounds_completed = 0
        self.fallback_exchanges = 0
        # 1-based exchange-period index, fed to faults.on_round() at each
        # period's entry — the deterministic injection point for KV-shard
        # chaos (DTF_CHAOS kill_kv_shard=I,at_round=K).  A period counter,
        # not rounds_completed: fallback periods count too, so at_round is
        # reproducible whatever path each period takes.
        self._period_index = 0
        #: per-stage wall-ms decomposition of the last exchange
        #: (intra_reduce / quantize / inter_exchange / broadcast — the
        #: bench's scaling arm and the telemetry record read this).
        self.last_stage_ms: dict[str, float] = {}
        # Last file COMMITTED per blob tag (kv_set of the pointer
        # succeeded): generation GC must never collect it, however many
        # failed-commit orphans pile generations on top — under a sharded
        # coordination plane one instance's kv_sets can fail for a while
        # on their own, and the pointer that instance keeps serving must
        # keep resolving (docs/param_exchange.md, "Hierarchical
        # exchange"; the per-instance-safety regression test).
        self._blob_refs: dict[str, str] = {}
        # Post-failover replay state (docs/fault_tolerance.md, "KV-shard
        # HA").  A dead primary acknowledges KVSET before the standby's
        # pull loop replicates it (lag up to lease/4), so a SIGKILL can
        # lose acknowledged WRITE-ONCE records — and a lost frozen
        # REDUCED record stalls every non-owner's consensus chain for
        # good (the single per-shard key is overwritten next round).
        # Cure: cache the newest payload published under every key and,
        # when the plane's failover count moves, re-publish the lot —
        # records are immutable per (epoch, round, shard), so the replay
        # is idempotent whether or not the write survived.  Memory stays
        # bounded: newest-per-key, quantized parts, same order as the
        # residual/consensus buffers already held.
        self._replay_pub: dict[str, tuple[list, str, bool, str]] = {}
        self._replay_kv: dict[str, str] = {}
        self._plane_failovers_seen = 0
        # Periods to keep my frozen-reduce REPLAYED round visible before
        # the next freeze overwrites its key: stalled peers get this many
        # periods to re-read the round the failover may have eaten.
        self._freeze_hold = 0
        #: completed post-failover replays (observability/tests).
        self.replays_completed = 0

    # ------------------------------------------------------ blob transport

    def _blob_tag(self, what: str) -> str:
        return f"task{self._task}.{what}"

    def _publish_blob(self, base_key: str, parts: list, tag: str,
                      compress: bool = True) -> int:
        """Publish a self-describing blob, transport chosen by size (the
        same rule as full-state publications); returns bytes-on-wire."""
        # Replay cache BEFORE the attempt: a publish whose pointer commit
        # failed outright (instance down) heals on the next replay too.
        # Parts are copied — callers pass views over mutable arrays.
        self._replay_pub[base_key] = (
            [bytes(memoryview(p).cast("B")) for p in parts], tag, compress,
            self._wire_scope)
        raw_len = sum(len(memoryview(p).cast("B")) for p in parts)
        if self._dir is not None and raw_len >= self._threshold:
            self._seq += 1
            fname, file_len, crc = write_blob_file(
                self._dir, tag, self._seq, parts, compress=compress)
            try:
                self._coord.kv_set(
                    base_key, f"v3blob {fname} {raw_len} {file_len} "
                              f"{crc:08x} {self._seq} "
                              f"{'z' if compress else 'r'}")
            except BaseException:
                # Failed commit (e.g. this key's coordination-plane
                # instance is down): the file just written is an orphan no
                # pointer will ever name.  Sweep the tag anyway so repeated
                # failures cannot grow the exchange dir unboundedly — the
                # sweep protects the last COMMITTED pointer's file.
                self._gc_blobs(tag)
                raise
            self._blob_refs[tag] = fname
            self._gc_blobs(tag)
            wire = file_len
            self.last_publish_transport = "sharded-binary"
        else:
            blob = b"".join(bytes(memoryview(p).cast("B")) for p in parts)
            payload = base64.b64encode(zlib.compress(blob, 1)).decode()
            publish_chunked(self._coord, base_key, payload)
            wire = len(payload)
            self.last_publish_transport = "sharded-kv"
        self._count_wire("out", wire)
        return wire

    def _gc_blobs(self, tag: str,
                  gc_keep: int = BINARY_GC_KEEP) -> None:
        # Generation-based, not seq-arithmetic: ``_seq`` is shared across
        # every tag this publisher writes (one bump per shard/reduced/
        # anchor blob), so consecutive generations of one tag differ by
        # more than 1 and ``old_seq <= seq - gc_keep`` would collapse
        # keep-last-3 into keep-only-current.  Keep the newest
        # ``gc_keep`` files of THIS tag, whatever their seq spacing.
        prefix = tag + "."
        try:
            names = os.listdir(self._dir)
        except OSError:
            return
        gens = []
        for old in names:
            if not (old.startswith(prefix) and old.endswith(".blob")):
                continue
            try:
                gens.append((int(old.rsplit(".", 2)[1]), old))
            except (IndexError, ValueError):
                continue
        gens.sort()
        # The last committed pointer's file is sacrosanct whatever its
        # generation: failed commits (a down KV instance under the sharded
        # plane) bump generations without moving the pointer, and the
        # instance that retained the pointer will serve it again.
        committed = self._blob_refs.get(tag)
        for _, old in gens[:-gc_keep]:
            if old == committed:
                continue
            try:
                os.unlink(os.path.join(self._dir, old))
            except OSError:
                pass

    def _fetch_blob(self, base_key: str) -> bytes | None:
        meta = self._coord.kv_get(base_key)
        if meta is None:
            return None
        if meta.startswith("v3blob"):
            parts = meta.split()
            if len(parts) != 7 or self._dir is None:
                return None
            try:
                raw_len, file_len, crc = (int(parts[2]), int(parts[3]),
                                          int(parts[4], 16))
            except ValueError:
                return None
            blob = read_blob_file(self._dir, parts[1], raw_len, file_len,
                                  crc, compressed=(parts[6] == "z"))
            if blob is not None:
                self._count_wire("in", file_len)
            return blob
        value = fetch_chunked(self._coord, base_key, meta=meta)
        if value is None:
            return None
        try:
            blob = zlib.decompress(base64.b64decode(value))
        except Exception:
            return None
        self._count_wire("in", len(value))
        return blob

    def _peer_fp_matches(self, peer: int) -> bool:
        """Once-loudly-then-skip structural gate for a peer's compressed
        records (``FP_KEY``): same rule as the legacy ``_fetch_peer``.  A
        missing fingerprint (peer hasn't published one yet) passes — the
        delta headers still gate round/epoch/size.  Matching values are
        cached; a mismatch is re-read every round so a peer restarted
        with the right model heals."""
        if self._fp is None:
            return True
        theirs = self._peer_fp.get(peer)
        if theirs is None:
            got = self._coord.kv_get(FP_KEY.format(self._ns, peer))
            if not got:
                return True
            self._count_wire("in", len(got))
            theirs = got
        if theirs == self._fp:
            self._peer_fp[peer] = theirs
            self._fp_mismatch_reported.discard(peer)
            return True
        if peer not in self._fp_mismatch_reported:
            self._fp_mismatch_reported.add(peer)
            self._print(
                f"[param_sync] ERROR: peer {peer} publishes a different "
                f"parameter tree (fingerprint {theirs} vs local "
                f"{self._fp}) — mixed model/dtype versions in one run; "
                f"its deltas are excluded from the compressed reduce "
                f"until it matches")
        self.fetch_skips[peer] = self.fetch_skips.get(peer, 0) + 1
        return False

    # ------------------------------------------------------ protocol state

    def _epoch_view(self) -> tuple[int, tuple[int, ...]]:
        if self._epoch_fn is None:
            return max(self._epoch, 0), tuple(range(self._num_workers))
        try:
            epoch, active = self._epoch_fn()
            active = tuple(sorted(t for t in active
                                  if 0 <= t < self._num_workers))
            if not active:
                raise ValueError("empty active set")
            return int(epoch), active
        except Exception:
            # Control-plane hiccup: keep the last agreed view — changing
            # the shard map on a one-sided error would fork ownership.
            return max(self._epoch, 0), self._active

    def _is_chief(self, active) -> bool:
        return bool(active) and min(active) == self._task

    def _anchor_key(self) -> str:
        return ANCHOR_KEY.format(self._ns)

    def _publish_anchor(self, epoch: int) -> None:
        if self._fp is not None:
            # Before the payload: once the anchor is visible, so is the
            # structural fingerprint adopters vet it against.  ``.tfp``,
            # not ``.fp`` — the chunked-KV transport owns ``<key>.fp``
            # and would clear it on every publish.
            self._set_hint(self._anchor_key() + ".tfp", self._fp)
        c = np.ascontiguousarray(self._consensus, np.float32)
        parts = encode_shard(c, kind=KIND_ANCHOR, fmt=FMT_RAW_F32,
                             round_=self._k, epoch=epoch, shard=0,
                             nshards=1, mask=1 << min(self._task, 31),
                             block=0)
        # Raw (not zlib) stream: anchors are full-precision weights —
        # incompressible — and the point of the anchor is exactness.
        self._publish_blob(self._anchor_key(), parts,
                           tag=self._blob_tag("anchor"), compress=False)
        # Cheap hint AFTER the payload commit: readers only use it to
        # decide whether re-fetching the (big) anchor is worth it, so a
        # stale hint costs one period of delay, never consistency.
        self._set_hint(self._anchor_key() + ".hint",
                       f"{self._k} {epoch}")

    def _fetch_anchor(self, n: int) -> tuple[int, np.ndarray] | None:
        afp = self._coord.kv_get(self._anchor_key() + ".tfp")
        if afp:
            self._count_wire("in", len(afp))
        if afp and self._fp is not None and afp != self._fp:
            # Same-size different-layout anchors would corrupt the
            # adopter silently; -1 keys the once-per-episode report.
            if -1 not in self._fp_mismatch_reported:
                self._fp_mismatch_reported.add(-1)
                self._print(
                    f"[param_sync] ERROR: the published anchor carries a "
                    f"different parameter tree (fingerprint {afp} vs "
                    f"local {self._fp}) — mixed model/dtype versions in "
                    f"one run; not adopting it")
            return None
        self._fp_mismatch_reported.discard(-1)
        blob = self._fetch_blob(self._anchor_key())
        decoded = decode_shard(blob) if blob is not None else None
        if decoded is None:
            return None
        hdr, vals = decoded
        if hdr["kind"] != KIND_ANCHOR or hdr["n_values"] != n:
            return None
        return hdr["round"], vals

    def _anchor_hint_round(self) -> int | None:
        hint = self._coord.kv_get(self._anchor_key() + ".hint")
        if not hint:
            return None
        try:
            return int(hint.split()[0])
        except (ValueError, IndexError):
            return None

    # ------------------------------------------------- failover replay

    def _set_hint(self, key: str, value: str) -> None:
        """A replayable version-hint/fingerprint kv_set: recorded in the
        replay cache (newest per key) before hitting the wire."""
        self._replay_kv[key] = value
        self._coord.kv_set(key, value)

    def _check_plane_failover(self) -> None:
        """Once per period, before any freeze: if the coordination plane
        rode a failover since last period, re-publish every cached
        write-once record (the promoted standby may have lost writes the
        dead primary acknowledged inside its replication-lag window) and
        hold my frozen-reduce for a couple of periods so peers stalled on
        a lost round get to re-read the replayed one before the next
        freeze overwrites its key."""
        pf = getattr(self._coord, "plane_failovers", None)
        if pf is None:
            return
        n = pf()
        if n > self._plane_failovers_seen:
            # Replay first, THEN advance the watermark: a replay cut short
            # by a plane still flapping retries next period (idempotent —
            # identical bytes per key).
            replayed = self._replay_published()
            self._plane_failovers_seen = n
            self._freeze_hold = 2
            self.replays_completed += 1
            self._print(
                f"[param_sync] task {self._task}: coordination failover "
                f"detected — replayed {replayed} published record(s) "
                f"(acknowledged writes inside the dead primary's "
                f"replication lag may have been lost); holding frozen "
                f"reduces for {self._freeze_hold} periods")
            if self._telemetry is not None:
                self._telemetry.emit(
                    "recovery", step=0, action="kv_replay",
                    records=replayed, plane_failovers=n)
        elif self._freeze_hold:
            self._freeze_hold -= 1

    def _replay_published(self) -> int:
        """Re-publish the newest cached payload under every key this
        worker has written: tree fingerprints first (readers vet payloads
        against them), then blobs, then the version hints that gate blob
        re-fetches (hint-after-payload, the normal commit discipline)."""
        n = 0
        for key, value in self._replay_kv.items():
            if key.endswith(".tfp"):
                self._coord.kv_set(key, value)
                n += 1
        for key, (parts, tag, compress, scope) in \
                list(self._replay_pub.items()):
            prev, self._wire_scope = self._wire_scope, scope
            try:
                self._publish_blob(key, parts, tag, compress=compress)
            finally:
                self._wire_scope = prev
            n += 1
        for key, value in self._replay_kv.items():
            if not key.endswith(".tfp"):
                self._coord.kv_set(key, value)
                n += 1
        return n

    def _reset_protocol(self) -> None:
        self._pending_reduce = None
        self._published_round = None
        self._reduced_done.clear()
        self._my_reduced.clear()
        self._peer_reduced.clear()
        self._my_delta = None
        self._snap = None
        # Scrub the failover-replay caches: shard/exporter/chief roles are
        # re-keyed by the new active set, so a stale cached REDUCED/CAST/
        # anchor payload replayed later could clobber a key now owned by
        # ANOTHER task.  Keep only my structural fingerprint (its key is
        # mine alone and never re-published by the steady state).
        fp_key = FP_KEY.format(self._ns, self._task)
        fp_val = self._replay_kv.get(fp_key)
        self._replay_pub.clear()
        self._replay_kv.clear()
        if fp_val is not None:
            self._replay_kv[fp_key] = fp_val

    def _sync_epoch(self, epoch: int, active, vec: np.ndarray) -> bool:
        """Adopt the membership epoch's shard map; True when a consensus
        is in hand (anchor adopted, carried over, or chief-published)."""
        epoch_changed = epoch != self._epoch
        self._epoch = epoch
        self._active = active
        if epoch_changed:
            self._reset_protocol()
        n = vec.size
        if self._consensus is not None and self._consensus.size == n:
            if epoch_changed and self._is_chief(active):
                # Epoch-change anchor: survivors re-anchor so evicted/
                # rejoining workers bootstrap against the new shard map.
                self._publish_anchor(epoch)
            return True
        got = self._fetch_anchor(n)
        if got is not None:
            self._k, self._consensus = got[0], got[1].copy()
            return True
        if self._is_chief(active):
            self._consensus = vec.copy()
            self._publish_anchor(epoch)
            return True
        return False

    # --------------------------------------------------------- the stages

    def _publish_delta(self, base: np.ndarray, epoch: int, active) -> None:
        if self._published_round == self._k:
            # This round's delta is already on the wire; local progress
            # since keeps accumulating in the params and rides the NEXT
            # round's delta (republishing fresher bytes peers may never
            # read would roughly double steady-state publish traffic).
            return
        d = base - self._consensus
        d += self._residual
        bounds = contiguous_shard_bounds(d.size, len(active))
        mask = contributor_bit(active, self._task)
        dq = np.empty_like(d)
        for j, (lo, hi) in enumerate(bounds):
            parts = encode_shard(d[lo:hi], kind=KIND_DELTA, fmt=self._fmt,
                                 round_=self._k, epoch=epoch, shard=j,
                                 nshards=len(active), mask=mask,
                                 block=self._block)
            dq[lo:hi] = dequantize_parts(parts, self._fmt, self._block)
            self._publish_blob(
                DELTA_KEY.format(self._ns, self._task, j), parts,
                tag=self._blob_tag(f"d{j}"))
        # Error feedback: what the quantizer dropped rides the NEXT delta.
        self._residual = d - dq
        self.last_residual_rms = float(
            np.sqrt(np.mean(np.square(self._residual)))) if d.size else 0.0
        self._my_delta = (self._k, dq)
        self._snap = base.copy()
        # First publication of this round (the early-return above filters
        # re-entries): peers get a full period to publish theirs before
        # the frozen reduce (next period) runs.
        self._published_round = self._k
        self._pending_reduce = self._k

    def _reduce_round(self, r: int, epoch: int, active, alive) -> None:
        """Freeze the reduced record(s) for the shards this worker owns at
        round ``r``: average every matching delta visible NOW (write-once
        per (epoch, round, shard) — late deltas ride their publishers'
        residuals into the next round instead of forking the record)."""
        if self._consensus is None:
            return
        bounds = contiguous_shard_bounds(self._consensus.size, len(active))
        my_bit = contributor_bit(active, self._task)
        mine = (self._my_delta[1]
                if self._my_delta is not None and self._my_delta[0] == r
                else None)
        for j, (lo, hi) in enumerate(bounds):
            if active[j] != self._task:
                continue
            if (epoch, r, j) in self._reduced_done:
                continue
            contribs, mask = [], 0
            if mine is not None:
                contribs.append(mine[lo:hi])
                mask |= my_bit
            for peer in active:
                if peer == self._task:
                    continue
                if alive is not None and peer < len(alive) \
                        and not alive[peer]:
                    continue
                if not self._peer_fp_matches(peer):
                    continue
                blob = self._fetch_blob(
                    DELTA_KEY.format(self._ns, peer, j))
                decoded = decode_shard(blob) if blob is not None else None
                if decoded is None:
                    continue
                hdr, vals = decoded
                if (hdr["kind"] == KIND_DELTA and hdr["round"] == r
                        and hdr["epoch"] == epoch
                        and hdr["nshards"] == len(active)
                        and hdr["n_values"] == hi - lo):
                    contribs.append(vals)
                    mask |= contributor_bit(active, peer)
            if not contribs:
                # Nothing to freeze yet (own delta lost to a restart and
                # no peer visible): re-arm so the round isn't orphaned.
                self._pending_reduce = r
                continue
            reduced = (contribs[0] if len(contribs) == 1
                       else np.mean(np.stack(contribs), axis=0))
            parts = encode_shard(np.ascontiguousarray(reduced, np.float32),
                                 kind=KIND_REDUCED, fmt=self._fmt,
                                 round_=r, epoch=epoch, shard=j,
                                 nshards=len(active), mask=mask,
                                 block=self._block)
            blob = b"".join(bytes(memoryview(p).cast("B")) for p in parts)
            key = REDUCED_KEY.format(self._ns, j)
            self._publish_blob(key, [blob], tag=self._blob_tag(f"r{j}"))
            # Version hint AFTER the payload commit: peers retrying an
            # assembly check these few bytes instead of refetching a
            # whole stale shard every period.
            self._set_hint(key + ".v", f"{r} {epoch}")
            # Cache my own frozen record (exact published bytes + its
            # contributor mask): assembly must use what peers will read,
            # but re-reading my own write isn't wire.
            self._my_reduced[(epoch, r, j)] = (decode_shard(blob)[1], mask)
            self._reduced_done.add((epoch, r, j))
        # Bound the bookkeeping: rounds older than a few periods can
        # never be assembled again.
        for key in [k for k in self._reduced_done if k[1] < r - 4]:
            self._reduced_done.discard(key)
            self._my_reduced.pop(key, None)
        for key in [k for k in self._peer_reduced if k[1] < r - 4]:
            self._peer_reduced.pop(key, None)

    def _try_assemble(self, vec: np.ndarray, epoch: int, active
                      ) -> tuple[np.ndarray | None, int]:
        """Advance the consensus chain from the frozen reduced shards of
        round ``self._k``; ``(None, 0)`` while any shard is missing."""
        r = self._k
        n = self._consensus.size
        bounds = contiguous_shard_bounds(n, len(active))
        my_bit = contributor_bit(active, self._task)
        shards = []
        for j, (lo, hi) in enumerate(bounds):
            cached = self._my_reduced.get((epoch, r, j))
            if cached is not None:
                shards.append((lo, hi) + cached)
                continue
            peer_cached = self._peer_reduced.get((epoch, r, j))
            if peer_cached is not None:
                shards.append((lo, hi) + peer_cached)
                continue
            # Version hint first: a shard whose owner hasn't frozen this
            # round yet costs a few bytes to discover, not a blob fetch.
            hint = self._coord.kv_get(REDUCED_KEY.format(self._ns, j) + ".v")
            if hint is not None:
                self._count_wire("in", len(hint))
                try:
                    hint_round, hint_epoch = (int(x) for x in hint.split())
                except ValueError:
                    hint_round = hint_epoch = None
                if (hint_round, hint_epoch) != (r, epoch):
                    return None, 0
            blob = self._fetch_blob(REDUCED_KEY.format(self._ns, j))
            decoded = decode_shard(blob) if blob is not None else None
            if decoded is None:
                return None, 0
            hdr, vals = decoded
            if not (hdr["kind"] == KIND_REDUCED and hdr["round"] == r
                    and hdr["epoch"] == epoch
                    and hdr["nshards"] == len(active)
                    and hdr["n_values"] == hi - lo):
                return None, 0
            # Frozen records are immutable: cache so a retried assembly
            # (other shards still missing) never refetches this one.
            self._peer_reduced[(epoch, r, j)] = (vals, hdr["mask"])
            shards.append((lo, hi, vals, hdr["mask"]))
        new_c = self._consensus.copy()
        union = 0
        for lo, hi, vals, mask in shards:
            new_c[lo:hi] += vals
            union |= mask
            if (not (mask & my_bit)
                    and self._my_delta is not None
                    and self._my_delta[0] == r):
                # My delta missed this frozen reduce: re-inject the
                # transmitted values so my progress rides the next round
                # (otherwise adopting the consensus would drop it).
                self._residual[lo:hi] += self._my_delta[1][lo:hi]
        # Delayed averaging with delta correction (the OverlappedAverager
        # equivalence): the consensus step computed from round-r snapshots
        # lands on TODAY's params, preserving local progress since.
        base = self._snap if (self._snap is not None
                              and self._snap.size == n) else self._consensus
        result = vec + (new_c - base)
        self._consensus = new_c
        self._k = r + 1
        self.rounds_completed += 1
        if self._is_chief(active) and self._k % self._anchor_every == 0:
            self._publish_anchor(epoch)
        peers = bin(union & ~my_bit).count("1")
        return result, peers

    def _maybe_adopt_anchor(self, n: int) -> np.ndarray | None:
        """Anchor-miss recovery: a laggard whose round fell behind the
        published anchor resynchronizes by adopting it, shifted by the
        consensus displacement so local progress survives."""
        hint = self._anchor_hint_round()
        if hint is None or hint <= self._k:
            return None
        got = self._fetch_anchor(n)
        if got is None or got[0] <= self._k:
            return None
        round_, anchor = got
        displacement = anchor - self._consensus
        self._k = round_
        self._consensus = anchor.copy()
        self._reset_protocol()
        self._print(f"[param_sync] task {self._task}: resynced to anchor "
                    f"round {round_} (was behind the consensus chain)")
        return displacement

    # ----------------------------------------------------------- the API

    def exchange(self, merged: Any, alive=None) -> tuple[Any, int]:
        """One compressed exchange period: frozen reduce of the pending
        round, consensus assembly, then this period's delta publication —
        falling back to the full-state path whenever the compressed
        protocol cannot run (non-float tree, no consensus reachable
        yet); a worker outside the membership epoch trains solo until
        readmitted (the legacy records are stale after bootstrap).

        A KV-shard failover mid-period is a bounded stall, not a lost
        round (docs/fault_tolerance.md, "KV-shard HA"), on two legs: the
        router's per-shard endpoint walk replays the IN-FLIGHT kv_set
        against the promoted standby, and ``_check_plane_failover``
        replays every ACKNOWLEDGED write-once record next period — the
        dead primary's replication lag (up to lease/4) can eat writes it
        acked, and a lost frozen REDUCED record would otherwise stall
        every non-owner's chain for good.  Both replays are idempotent:
        records are immutable per (epoch, round, shard)."""
        self._period_index += 1
        faults.on_round(self._period_index)
        t0 = time.perf_counter()
        t0_unix = time.time()
        self.last_bytes_out = self.last_bytes_in = 0
        self.last_intra_bytes = 0
        host = jax.tree.map(np.asarray, merged)
        leaves = jax.tree.leaves(host)
        if not leaves or not all(_float_dtype(l.dtype) for l in leaves):
            if not self._warned_nonfloat:
                self._warned_nonfloat = True
                self._print(f"[param_sync] task {self._task}: parameter "
                            "tree has non-float leaves — compressed "
                            "exchange disabled, using the full-state path")
            self.fallback_exchanges += 1
            self._note_extra = {"fallback": True, "reason": "non_float"}
            return super().exchange(merged, alive)
        if self._fp is None:
            self._fp = tree_fingerprint(host)
        if not self._fp_published:
            # On the wire BEFORE any delta/anchor of mine, so readers can
            # always vet my records structurally.
            self._set_hint(FP_KEY.format(self._ns, self._task), self._fp)
            self._count_wire("out", len(self._fp))
            self._fp_published = True
        epoch, active = self._epoch_view()
        if self._task not in active:
            # Evicted/not-yet-admitted this epoch: keep training SOLO.
            # The legacy full-state records were last refreshed during
            # bootstrap (steady-state compressed rounds never republish
            # them), so super().exchange() here would average live
            # weights with round-one-era snapshots and regress the loss;
            # readmission re-keys shard ownership at the next epoch and
            # the anchor resync picks this worker back up.
            self.fallback_exchanges += 1
            self._note_extra = {"fallback": True, "reason": "not_member",
                                "epoch": epoch}
            self._note_exchange(
                peers=0,
                native_bytes=sum(m[2] for m in map(_leaf_meta, leaves)),
                compressed=False,
                dur_ms=(time.perf_counter() - t0) * 1000.0)
            return merged, 0
        vec = _flatten_f32(host)
        native_bytes = sum(m[2] for m in map(_leaf_meta, leaves))
        if self._residual is None or self._residual.size != vec.size:
            self._residual = np.zeros(vec.size, np.float32)
        if not self._sync_epoch(epoch, active, vec):
            # No consensus reachable (anchor chief hasn't published yet):
            # the full-state exchange IS the bootstrap fallback.
            self.fallback_exchanges += 1
            self._note_extra = {"fallback": True, "reason": "no_anchor",
                                "round": self._k, "epoch": epoch}
            return ParamAverager.exchange(self, merged, alive)
        # Before any freeze this period: replay write-once records if the
        # plane rode a failover since last period (the dead primary's
        # replication lag may have eaten acknowledged writes).
        self._check_plane_failover()
        return self._run_protocol(merged, host, vec, epoch, active, alive,
                                  native_bytes, t0, t0_unix)

    def _run_protocol(self, merged, host, vec, epoch, active, alive,
                      native_bytes, t0, t0_unix):
        """One flat compressed period (consensus in hand): frozen reduce
        of the pending round, assembly, this period's delta publication.
        The seam the hierarchical subclass overrides with its two-level
        protocol."""
        tr0 = time.perf_counter()
        if self._pending_reduce is not None and not self._freeze_hold:
            pending, self._pending_reduce = self._pending_reduce, None
            try:
                self._reduce_round(pending, epoch, active, alive)
            except BaseException:
                # A transport blip must not orphan the round: without my
                # frozen shard the whole fleet's chain stalls forever.
                # Re-arm so the next period retries (idempotent — the
                # write-once ``_reduced_done`` guard skips frozen shards).
                self._pending_reduce = pending
                raise
        reduce_ms = (time.perf_counter() - tr0) * 1000.0
        ta0 = time.perf_counter()
        result, peers = self._try_assemble(vec, epoch, active)
        if result is None:
            displacement = self._maybe_adopt_anchor(vec.size)
            if displacement is not None:
                result = vec + displacement
        assemble_ms = (time.perf_counter() - ta0) * 1000.0
        tp0 = time.perf_counter()
        self._publish_delta(result if result is not None else vec,
                            epoch, active)
        publish_ms = (time.perf_counter() - tp0) * 1000.0
        dur_ms = (time.perf_counter() - t0) * 1000.0
        self.last_stage_ms = {
            "intra_reduce_ms": 0.0,
            "quantize_ms": round(publish_ms, 3),
            "inter_exchange_ms": round(reduce_ms + assemble_ms, 3),
            "broadcast_ms": 0.0,
        }
        tracer = tracing.active()
        if tracer is not None:
            span = tracer.emit_span("exchange", t0_unix, dur_ms,
                                    round=self._k, epoch=epoch, peers=peers)
            off = t0_unix
            for name, ms in (("exchange.reduce", reduce_ms),
                             ("exchange.assemble", assemble_ms),
                             ("exchange.publish", publish_ms)):
                tracer.emit_span(name, off, ms, parent_id=span)
                off += ms / 1000.0
        self._note_exchange(
            peers=peers, native_bytes=native_bytes, compressed=True,
            round=self._k, epoch=epoch, advanced=result is not None,
            residual_rms=round(self.last_residual_rms, 6),
            quant="int8" if self._fmt == FMT_INT8 else "bf16",
            stages=self.last_stage_ms,
            dur_ms=dur_ms)
        if result is None:
            return merged, 0
        return _unflatten_f32(result, host), peers

    def pull_latest(self, template: Any) -> Any | None:
        """Rejoin bootstrap: the anchor (the collective's agreed
        consensus) first, the legacy full-state average as fallback."""
        host = jax.tree.map(np.asarray, template)
        leaves = jax.tree.leaves(host)
        if leaves and all(_float_dtype(l.dtype) for l in leaves):
            if self._fp is None:
                self._fp = tree_fingerprint(host)
            n = sum(np.asarray(l).size for l in leaves)
            got = self._fetch_anchor(n)
            if got is not None:
                return _unflatten_f32(got[1], host)
        return super().pull_latest(template)


class HierarchicalCompressedAverager(CompressedShardedAverager):
    """Two-level compressed exchange: intra-slice raw reduction, ONE
    quantized inter-slice shard exchange per slice (docs/param_exchange.md,
    "Hierarchical exchange").

    Workers group into **slices** via the topology map
    (``parallel.sync.slice_topology`` over the membership epoch's active
    set).  Within a slice the delta is reduced RAW — ICI/shared-memory is
    cheap, so no quantization and none of it counts as inter-host wire
    bytes; when the slice's members are local mesh replicas the reduce is
    a jitted ``psum`` (``parallel.sync.build_intra_slice_reduce``), and
    when they are sibling worker processes it rides raw float32 records
    over the exchange dir/KV (the CI simulation of the ICI hop).  Exactly
    one **exporter** per slice (lowest task id) quantizes the
    slice-reduced delta with the inherited int8+error-feedback codec and
    runs the flat protocol's shard exchange against the other slices'
    exporters — so per-host inter-host bytes drop from O(2P/N·N) to
    O(2P/S) with S slices, and the consensus chain is keyed by
    (epoch, round, slice, shard) through the exporter identity.
    Non-exporters receive the assembled consensus via an intra-slice
    broadcast record and apply it with the same delayed-averaging delta
    correction.

    Slice membership and exporter election re-derive from the elastic
    epoch: an evicted exporter is just the PR-5 evicted-owner machinery
    one level up — the next epoch re-keys its slice to the surviving
    lowest task and the chief re-anchors.

    Contributor masks are POSITION-based per exchange group
    (:func:`contributor_bit`), so the u32 mask covers 32 slices of 32
    members each — the arithmetic that makes "hundreds of workers"
    plausible where the flat protocol stops at 32.
    """

    MAX_GROUP = 32 * 32

    def __init__(self, coord, task_index: int, num_workers: int,
                 namespace: str = "default",
                 exchange_dir: str | None = None,
                 binary_threshold: int = BINARY_THRESHOLD_BYTES,
                 print_fn=print, quant: str = "int8",
                 block: int = DEFAULT_QUANT_BLOCK,
                 anchor_every: int = DEFAULT_ANCHOR_EVERY,
                 epoch_fn=None, slice_size: int = 2,
                 intra_reduce_fn=None):
        super().__init__(coord, task_index, num_workers,
                         namespace=namespace, exchange_dir=exchange_dir,
                         binary_threshold=binary_threshold,
                         print_fn=print_fn, quant=quant, block=block,
                         anchor_every=anchor_every, epoch_fn=epoch_fn)
        if slice_size < 1:
            raise ValueError(f"slice_size must be >= 1, got {slice_size}")
        if slice_size > 32 or -(-num_workers // slice_size) > 32:
            raise ValueError(
                f"hierarchical exchange supports at most 32 slices of at "
                f"most 32 members (u32 contributor masks per level): "
                f"slice_size={slice_size} over {num_workers} workers "
                f"doesn't fit")
        self._slice_size = slice_size
        #: optional jitted AllReduce ``(stacked [k, n]) -> mean [n]``
        #: (``parallel.sync.build_intra_slice_reduce``) used for the slice
        #: mean when provided; host ``np.mean`` otherwise.
        self._intra_reduce_fn = intra_reduce_fn
        # Exporter bookkeeping: intra contributor mask per frozen round
        # (carried on that round's broadcast) and the one-period arming
        # that gives members a period to publish before the freeze.
        self._cast_mask: dict[int, int] = {}
        self._armed_round: int | None = None
        #: last period's placement (bench/observability).
        self.last_slice: int | None = None
        self.last_is_exporter = False

    def _reset_protocol(self) -> None:
        super()._reset_protocol()
        self._cast_mask.clear()
        self._armed_round = None

    def _slice_view(self, active):
        slices = slice_topology(active, self._slice_size)
        g = slice_of_task(slices, self._task)
        return slices, g

    def _cast_key(self, g: int) -> str:
        return CAST_KEY.format(self._ns, g)

    # ------------------------------------------------------ member side

    def _member_adopt(self, vec: np.ndarray, epoch: int, g: int,
                      members) -> tuple[np.ndarray | None, int]:
        """Adopt the exporter's consensus broadcast, if one for my round
        (or later — the laggard resync) is up; ``(None, 0)`` otherwise."""
        hint = self._coord.kv_get(self._cast_key(g) + ".v")
        if hint is not None:
            with self._intra():
                self._count_wire("in", len(hint))
            try:
                hint_round, hint_epoch = (int(x) for x in hint.split())
            except ValueError:
                return None, 0
            if hint_round < self._k or hint_epoch != epoch:
                return None, 0
        with self._intra():
            blob = self._fetch_blob(self._cast_key(g))
        decoded = decode_shard(blob) if blob is not None else None
        if decoded is None:
            return None, 0
        hdr, new_c = decoded
        if (hdr["kind"] != KIND_CAST or hdr["epoch"] != epoch
                or hdr["shard"] != g or hdr["n_values"] != vec.size):
            return None, 0
        r = hdr["round"]
        my_bit = contributor_bit(members, self._task)
        if r == self._k:
            # The round I contributed to assembled: delayed averaging
            # with delta correction against MY snapshot.
            base = self._snap if (self._snap is not None
                                  and self._snap.size == vec.size) \
                else self._consensus
            result = vec + (new_c - base)
            if (not (hdr["mask"] & my_bit)
                    and self._my_delta is not None
                    and self._my_delta[0] == r):
                # My raw delta missed the exporter's freeze: re-inject so
                # my progress rides the next round instead of being lost.
                self._residual += self._my_delta[1]
        elif r > self._k:
            # I lagged several rounds (slow cadence, restart): adopt by
            # consensus displacement, keeping local progress — the
            # intra-slice analogue of the anchor-miss resync.
            result = vec + (new_c - self._consensus)
            self._print(f"[param_sync] task {self._task}: resynced to "
                        f"slice {g} broadcast round {r} (was at round "
                        f"{self._k})")
        else:
            return None, 0
        self._consensus = new_c.copy()
        self._k = r + 1
        self.rounds_completed += 1
        self._published_round = None
        self._my_delta = None
        peers = bin(hdr["mask"] & ~my_bit).count("1")
        return result, peers

    def _member_publish(self, cur: np.ndarray, epoch: int, g: int,
                        members) -> None:
        """Publish my RAW float32 delta for the current round into the
        slice — once per round, error-free (raw), so the residual resets
        to the re-injection vehicle it is for members."""
        if self._published_round == self._k:
            return
        d = cur - self._consensus
        d += self._residual
        parts = encode_shard(np.ascontiguousarray(d, np.float32),
                             kind=KIND_DELTA, fmt=FMT_RAW_F32,
                             round_=self._k, epoch=epoch, shard=g,
                             nshards=len(members),
                             mask=contributor_bit(members, self._task),
                             block=0)
        with self._intra():
            self._publish_blob(
                MEMBER_DELTA_KEY.format(self._ns, g, self._task), parts,
                tag=self._blob_tag(f"m{g}"))
        self._my_delta = (self._k, d.copy())
        self._snap = cur.copy()
        self._residual = np.zeros_like(self._residual)
        self._published_round = self._k

    # ---------------------------------------------------- exporter side

    def _freeze_slice_delta(self, vec: np.ndarray, epoch: int, g: int,
                            members, exporters, alive) -> float:
        """Freeze the slice-reduced delta for the current round — mean of
        every member delta visible NOW plus my own — and publish it as my
        quantized inter-slice delta.  Returns the quantize+publish ms."""
        mask = contributor_bit(members, self._task)
        member_ds = []
        for peer in members:
            if peer == self._task:
                continue
            if alive is not None and peer < len(alive) and not alive[peer]:
                continue
            with self._intra():
                fp_ok = self._peer_fp_matches(peer)
            if not fp_ok:
                continue
            with self._intra():
                blob = self._fetch_blob(
                    MEMBER_DELTA_KEY.format(self._ns, g, peer))
            decoded = decode_shard(blob) if blob is not None else None
            if decoded is None:
                continue
            hdr, vals = decoded
            if (hdr["kind"] == KIND_DELTA and hdr["round"] == self._k
                    and hdr["epoch"] == epoch
                    and hdr["n_values"] == vec.size):
                member_ds.append(vals)
                mask |= contributor_bit(members, peer)
        own_d = vec - self._consensus
        if member_ds:
            stacked = np.stack([own_d] + member_ds)
            if (self._intra_reduce_fn is not None
                    and stacked.shape[0] == len(members)):
                # Jitted psum AllReduce — ONLY for a full house: the
                # shard_map is compiled for exactly len(members) rows and
                # divides by that count, so a partial set (a slow/evicted
                # member, a fingerprint mismatch) must take the host mean
                # below — with the CONTRIBUTOR count as divisor — rather
                # than crash the exchange or mis-scale the slice delta.
                slice_delta = np.asarray(self._intra_reduce_fn(stacked),
                                         np.float32)
            else:
                slice_delta = np.mean(stacked, axis=0, dtype=np.float32)
        else:
            slice_delta = own_d
        self._cast_mask[self._k] = mask
        tq0 = time.perf_counter()
        # The inherited flat protocol over the EXPORTER group: quantize
        # (int8 + error feedback at this level) and shard-publish.
        self._publish_delta(self._consensus + slice_delta, epoch,
                            exporters)
        # _publish_delta snapshots the virtual slice base; the delayed-
        # averaging correction for MY params needs MY base.
        self._snap = vec.copy()
        return (time.perf_counter() - tq0) * 1000.0

    def _broadcast_consensus(self, r: int, epoch: int, g: int,
                             members) -> None:
        """Publish the assembled consensus back into the slice (raw f32,
        intra-class traffic), carrying round r's intra contributor mask so
        excluded members self-detect."""
        if len(members) == 1:
            self._cast_mask.pop(r, None)
            return  # singleton slice: nobody to tell
        mask = self._cast_mask.pop(
            r, contributor_bit(members, self._task))
        parts = encode_shard(
            np.ascontiguousarray(self._consensus, np.float32),
            kind=KIND_CAST, fmt=FMT_RAW_F32, round_=r, epoch=epoch,
            shard=g, nshards=len(members), mask=mask, block=0)
        with self._intra():
            self._publish_blob(self._cast_key(g), parts,
                               tag=self._blob_tag(f"cast{g}"),
                               compress=False)
        self._set_hint(self._cast_key(g) + ".v", f"{r} {epoch}")

    # ---------------------------------------------------------- protocol

    def _run_protocol(self, merged, host, vec, epoch, active, alive,
                      native_bytes, t0, t0_unix):
        slices, g = self._slice_view(active)
        if g is None:  # unreachable (active membership checked upstream)
            return merged, 0
        members = slices[g]
        exporters = slice_exporters(slices)
        if len(slices) > 32 or len(members) > 32:
            raise ValueError(
                f"hierarchical exchange derived {len(slices)} slices with "
                f"a largest slice of {max(len(s) for s in slices)} members "
                f"— both must be <= 32 (u32 contributor masks); adjust "
                f"--slice_size")
        self.last_slice = g
        self.last_is_exporter = is_exporter = members[0] == self._task
        intra_ms = quant_ms = inter_ms = cast_ms = 0.0
        advanced_round = None
        if is_exporter:
            # Frozen inter-slice reduce of the pending round, then
            # assembly — the inherited machinery over the exporter group.
            ti0 = time.perf_counter()
            if self._pending_reduce is not None and not self._freeze_hold:
                pending, self._pending_reduce = self._pending_reduce, None
                try:
                    self._reduce_round(pending, epoch, exporters, alive)
                except BaseException:
                    self._pending_reduce = pending  # re-arm, never orphan
                    raise
            result, peers = self._try_assemble(vec, epoch, exporters)
            if result is None:
                displacement = self._maybe_adopt_anchor(vec.size)
                if displacement is not None:
                    result = vec + displacement
            else:
                advanced_round = self._k - 1
            inter_ms = (time.perf_counter() - ti0) * 1000.0
            tc0 = time.perf_counter()
            if advanced_round is not None:
                self._broadcast_consensus(advanced_round, epoch, g,
                                          members)
            cast_ms = (time.perf_counter() - tc0) * 1000.0
            # Freeze + publish the NEXT round's slice delta one period
            # after the round opened, so members have had a period to see
            # the broadcast and publish their deltas into the slice.
            ti1 = time.perf_counter()
            if self._published_round != self._k:
                if self._armed_round == self._k:
                    quant_ms = self._freeze_slice_delta(
                        vec, epoch, g, members, exporters, alive)
                else:
                    self._armed_round = self._k
            intra_ms += (time.perf_counter() - ti1) * 1000.0 - quant_ms
        else:
            tb0 = time.perf_counter()
            result, peers = self._member_adopt(vec, epoch, g, members)
            cast_ms = (time.perf_counter() - tb0) * 1000.0
            ti0 = time.perf_counter()
            self._member_publish(result if result is not None else vec,
                                 epoch, g, members)
            intra_ms = (time.perf_counter() - ti0) * 1000.0
        dur_ms = (time.perf_counter() - t0) * 1000.0
        self.last_stage_ms = {
            "intra_reduce_ms": round(max(intra_ms, 0.0), 3),
            "quantize_ms": round(quant_ms, 3),
            "inter_exchange_ms": round(inter_ms, 3),
            "broadcast_ms": round(cast_ms, 3),
        }
        tracer = tracing.active()
        if tracer is not None:
            span = tracer.emit_span("exchange", t0_unix, dur_ms,
                                    round=self._k, epoch=epoch,
                                    peers=peers, slice=g,
                                    exporter=is_exporter)
            # Child spans in each role's REAL execution order (exporter:
            # inter reduce/assemble -> broadcast -> member-delta fetch ->
            # quantize+publish; member: broadcast adopt -> raw publish),
            # so the exported timeline attributes latency to the stage
            # that actually occupied it.
            if is_exporter:
                order = (("exchange.inter_exchange", inter_ms),
                         ("exchange.broadcast", cast_ms),
                         ("exchange.intra_reduce", intra_ms),
                         ("exchange.quantize", quant_ms))
            else:
                order = (("exchange.broadcast", cast_ms),
                         ("exchange.intra_reduce", intra_ms))
            off = t0_unix
            for name, ms in order:
                tracer.emit_span(name, off, ms, parent_id=span)
                off += ms / 1000.0
        self._note_exchange(
            peers=peers, native_bytes=native_bytes, compressed=True,
            round=self._k, epoch=epoch, advanced=result is not None,
            residual_rms=round(self.last_residual_rms, 6),
            quant="int8" if self._fmt == FMT_INT8 else "bf16",
            hierarchical=True, slice=g, n_slices=len(slices),
            exporter=is_exporter,
            inter_bytes=self.last_bytes_out + self.last_bytes_in,
            intra_bytes=self.last_intra_bytes,
            stages=self.last_stage_ms,
            dur_ms=dur_ms)
        if result is None:
            return merged, 0
        return _unflatten_f32(result, host), peers


class OverlappedAverager:
    """Background-threaded parameter exchange — the GB-scale publish/
    fetch/average runs CONCURRENTLY with training instead of stalling it
    (VERDICT r4 #5: a 1.1 GB / 2-peer exchange measured 36 s of
    stop-the-world pause per sync period; the reference PS moved
    parameters concurrently with other workers' compute every step,
    ``distributed.py:145``).

    Protocol (delayed averaging with delta correction):

    - at each sync period the trainer hands over a host SNAPSHOT of its
      merged params and immediately keeps training;
    - the worker thread publishes the snapshot, fetches live peers, and
      averages — all while local steps continue;
    - at the NEXT period the trainer collects the finished average and
      applies it as a DELTA against the snapshot it came from
      (``params += avg - snapshot``): the consensus pull lands one
      period late, but the K local steps taken meanwhile are preserved
      instead of overwritten (plain stale adoption would silently undo
      them — that is the difference between "delayed averaging" and
      "losing a period of work").

    Equivalence: with the delta applied, the update at period n is
    exactly the synchronous exchange's update computed from period
    n-1's parameters — the same math one period stale, which is inside
    the bounded-staleness contract async mode already documents (peers
    read whatever publications exist; nobody waits).  Pinned by
    ``tests/test_param_sync.py::test_overlapped_matches_one_period_stale_sync``.

    One exchange is in flight at a time; if the previous one has not
    finished by the next period, the trainer simply keeps training and
    retries collection a period later (the exchange thread never blocks
    the step loop — that is the whole point).
    """

    def __init__(self, averager: ParamAverager, alive_fn=None,
                 print_fn=print):
        import queue
        import threading
        self._avg = averager
        self._alive_fn = alive_fn
        self._print = print_fn
        self._in: "queue.Queue" = queue.Queue(maxsize=1)
        self._out: "queue.Queue" = queue.Queue(maxsize=1)
        self._busy = False
        self._closed = False
        #: wall seconds the last background exchange took (observability)
        self.last_exchange_seconds = 0.0
        self.exchanges_completed = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="param-exchange")
        self._thread.start()

    def _loop(self):
        import queue
        import time
        while True:
            snapshot = self._in.get()
            if snapshot is None or self._closed:
                return
            t0 = time.perf_counter()
            try:
                alive = self._alive_fn() if self._alive_fn else None
                avg, peers = self._avg.exchange(snapshot, alive=alive)
            except Exception as e:
                # Control-plane hiccups (a peer evicted mid-exchange, an
                # unreachable coordinator) must not kill the thread; report
                # a no-op result so the trainer just continues.
                self._print(f"[param_sync] background exchange failed "
                            f"({type(e).__name__}: {e}); skipping period")
                avg, peers = snapshot, 0
            self.last_exchange_seconds = time.perf_counter() - t0
            if self._closed:
                return  # nobody will collect; exit instead of blocking
            try:
                self._out.put_nowait((avg, snapshot, peers))
            except queue.Full:  # pragma: no cover — busy-flag protocol
                pass            # prevents this; defensive against a leak

    @property
    def busy(self) -> bool:
        """True while an exchange is in flight AND its result has not
        been collected yet.  Callers should check this BEFORE
        materializing a snapshot — a device-to-host copy of a GB tree
        that ``submit`` would refuse is exactly the stall this class
        exists to hide."""
        return self._busy

    def poll(self) -> tuple[Any, Any, int] | None:
        """Collect the finished exchange, if any: ``(avg, snapshot,
        peers)`` — apply ``params += avg - snapshot`` when ``peers > 0``
        — or None while still in flight / nothing launched."""
        import queue
        if not self._busy:
            return None
        try:
            result = self._out.get_nowait()
        except queue.Empty:
            self._print("[param_sync] background exchange still in "
                        "flight; continuing to train (will collect "
                        "next period)")
            return None
        self._busy = False
        self.exchanges_completed += 1
        return result

    def submit(self, merged_host: Any) -> bool:
        """Launch the next background exchange with this host snapshot;
        False (snapshot unused) when one is already in flight."""
        if self._busy:
            return False
        self._in.put(merged_host)
        self._busy = True
        return True

    def step_period(self, merged_host: Any) -> tuple[Any, Any, int] | None:
        """poll() + submit() in one call, for callers whose snapshot is
        already host-side (tests, the bench overlap arm)."""
        result = self.poll()
        self.submit(merged_host)
        return result

    def drain(self, timeout: float | None = None):
        """Block for the in-flight exchange (end of training / tests).
        Returns the final ``(avg, snapshot, peers)`` or None."""
        import queue
        if not self._busy:
            return None
        try:
            result = self._out.get(timeout=timeout)
        except queue.Empty:
            return None
        self._busy = False
        self.exchanges_completed += 1
        return result

    def close(self, timeout: float = 30.0) -> bool:
        """Stop the worker thread and JOIN it.  Safe while an exchange is
        in flight (a peer evicted mid-exchange leaves the thread inside
        the coordination client's retry budget — it finishes or no-ops,
        sees the closed flag, and exits); the sentinel is delivered
        without blocking even if a snapshot is still queued.  Returns
        True when the thread is confirmed dead — the regression surface
        for the thread-leak bug where close() neither joined nor could
        outlive a full input queue."""
        import queue
        self._closed = True
        try:
            self._in.put_nowait(None)
        except queue.Full:
            pass  # worker is mid-get; it checks _closed on its next loop
        self._thread.join(timeout)
        return not self._thread.is_alive()


def run_namespace(logdir: str) -> str:
    """Stable per-run KV namespace: a digest of the run's logdir (shared by
    all of the run's workers and its restarts; different for fresh runs)."""
    import os
    import zlib as _zlib
    return format(_zlib.crc32(os.path.abspath(logdir).encode()), "08x")
