"""Cross-process asynchronous parameter averaging over the control plane.

The reference's async mode is Hogwild through the parameter server: every
worker pushes and pulls at its own cadence, and the parameters survive
worker death on the PS (reference ``distributed.py:102``; SURVEY N2/N4).
TPU-natively the data plane moved into HBM + ICI collectives — but ICI
collectives are lockstep.  For *independent-cadence* async across worker
processes, this module re-creates the PS exchange at the control plane:

- each worker periodically publishes its (locally merged) parameters to the
  coordination service's KV store and averages in whatever peers have
  published — no barrier, bounded staleness, workers never wait on each
  other (the reference's stale-update semantics, without the races);
- published parameters survive on the service across worker restarts (and —
  with the coordinator's KV journal — across coordinator restarts too), so a
  rejoining worker pulls the collective's current state — the PS-durability
  role the reference relied on.

Size: payloads (zlib-compressed float32, base64) are **chunked** across
multiple KV entries with a meta entry written last as the commit point, so
model size is bounded by coordinator memory, not the wire protocol's
request-line cap — matching the reference PS, which moved full models every
step (``distributed.py:145``).  A torn read (meta/chunk mismatch while a
peer republishes) fails the checksum and that peer is skipped for the round.
"""

from __future__ import annotations

import base64
import zlib
from typing import Any

import jax
import numpy as np

KEY_FORMAT = "dtf/async_params/{}/task{}"
# Chunk size in base64 chars: comfortably under the coordinator's 8 MiB
# request-line cap and the client's initial response buffer.
CHUNK_CHARS = 512 * 1024


def _encode(params: Any) -> str:
    leaves = [np.asarray(l, np.float32).ravel() for l in jax.tree.leaves(params)]
    buf = np.concatenate(leaves) if leaves else np.zeros((0,), np.float32)
    return base64.b64encode(zlib.compress(buf.tobytes(), level=1)).decode()


def _decode(value: str, template: Any) -> Any | None:
    leaves, treedef = jax.tree.flatten(template)
    try:
        raw = zlib.decompress(base64.b64decode(value))
    except Exception:
        return None
    flat = np.frombuffer(raw, np.float32)
    total = sum(int(np.prod(l.shape)) for l in leaves)
    if flat.size != total:
        return None  # peer published a different model/shape — skip it
    out, pos = [], 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[pos:pos + n].reshape(l.shape))
        pos += n
    return jax.tree.unflatten(treedef, out)


def publish_chunked(coord, base_key: str, payload: str,
                    chunk_chars: int = CHUNK_CHARS) -> int:
    """Write ``payload`` as ``<base>.c<i>`` chunks, then the ``<base>`` meta
    entry (``v1 <nchunks> <len> <crc32>``) as the commit point.  Returns the
    chunk count."""
    nchunks = max(1, -(-len(payload) // chunk_chars))
    for i in range(nchunks):
        coord.kv_set(f"{base_key}.c{i}",
                     payload[i * chunk_chars:(i + 1) * chunk_chars])
    crc = zlib.crc32(payload.encode())
    coord.kv_set(base_key, f"v1 {nchunks} {len(payload)} {crc:08x}")
    return nchunks


def fetch_chunked(coord, base_key: str) -> str | None:
    """Read a chunked payload; None when absent or torn (checksum/length
    mismatch against the meta entry)."""
    meta = coord.kv_get(base_key)
    if meta is None:
        return None
    parts = meta.split()
    if len(parts) != 4 or parts[0] != "v1":
        return None
    try:
        nchunks, total, crc = int(parts[1]), int(parts[2]), int(parts[3], 16)
    except ValueError:
        return None
    chunks = []
    for i in range(nchunks):
        chunk = coord.kv_get(f"{base_key}.c{i}")
        if chunk is None:
            return None
        chunks.append(chunk)
    payload = "".join(chunks)
    if len(payload) != total or zlib.crc32(payload.encode()) != crc:
        return None
    return payload


class ParamAverager:
    """Publish/average merged parameters through the coordination KV.

    ``namespace`` scopes the KV keys to one run (callers pass a digest of
    the run's logdir): a restarted worker of the SAME run rejoins its
    collective, while a fresh run against a still-running coordination
    service never adopts a dead run's weights.
    """

    def __init__(self, coord, task_index: int, num_workers: int,
                 namespace: str = "default"):
        self._coord = coord
        self._task = task_index
        self._num_workers = num_workers
        self._ns = namespace

    def _key(self, task: int) -> str:
        return KEY_FORMAT.format(self._ns, task)

    def exchange(self, merged: Any, alive=None) -> tuple[Any, int]:
        """Publish ``merged`` (host-side average of local replicas), pull
        live peers' publications, and return
        ``(averaged_params, num_peers_included)``.

        Peers that haven't published yet (slower cadence, just restarted)
        are simply absent — nobody blocks; that IS the async contract.
        ``alive`` (per-task liveness bits from the heartbeat health cache)
        excludes dead/finished peers, whose frozen snapshots would otherwise
        anchor the average forever.
        """
        host_merged = jax.tree.map(lambda x: np.asarray(x, np.float32), merged)
        publish_chunked(self._coord, self._key(self._task),
                        _encode(host_merged))
        contributions = [host_merged]
        for task in range(self._num_workers):
            if task == self._task:
                continue
            if alive is not None and task < len(alive) and not alive[task]:
                continue
            value = fetch_chunked(self._coord, self._key(task))
            if value is None:
                continue
            peer = _decode(value, host_merged)
            if peer is not None:
                contributions.append(peer)
        n = len(contributions)
        if n == 1:
            return merged, 0
        avg = jax.tree.map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *contributions)
        return avg, n - 1

    def pull_latest(self, template: Any) -> Any | None:
        """Average of everything published in this run's namespace
        (restart-and-rejoin: a rejoining worker adopts the collective's
        state instead of step 1 — stale entries are exactly the durability
        this provides, so liveness is deliberately NOT checked here)."""
        contributions = []
        for task in range(self._num_workers):
            value = fetch_chunked(self._coord, self._key(task))
            if value is None:
                continue
            peer = _decode(value, template)
            if peer is not None:
                contributions.append(peer)
        if not contributions:
            return None
        return jax.tree.map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *contributions)


def run_namespace(logdir: str) -> str:
    """Stable per-run KV namespace: a digest of the run's logdir (shared by
    all of the run's workers and its restarts; different for fresh runs)."""
    import os
    import zlib as _zlib
    return format(_zlib.crc32(os.path.abspath(logdir).encode()), "08x")
