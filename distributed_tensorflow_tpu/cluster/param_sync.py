"""Cross-process asynchronous parameter averaging over the control plane.

The reference's async mode is Hogwild through the parameter server: every
worker pushes and pulls at its own cadence, and the parameters survive
worker death on the PS (reference ``distributed.py:102``; SURVEY N2/N4).
TPU-natively the data plane moved into HBM + ICI collectives — but ICI
collectives are lockstep.  For *independent-cadence* async across worker
processes, this module re-creates the PS exchange at the control plane:

- each worker periodically publishes its (locally merged) parameters to the
  coordination service's KV store and averages in whatever peers have
  published — no barrier, bounded staleness, workers never wait on each
  other (the reference's stale-update semantics, without the races);
- published parameters survive on the service across worker restarts (and —
  with the coordinator's KV journal — across coordinator restarts too), so a
  rejoining worker pulls the collective's current state — the PS-durability
  role the reference relied on.

Payloads travel in the parameters' OWN dtype: a bf16 model moves half the
bytes a float32 encoding would (the r3 float32 pin doubled every bf16
exchange), and averaging upcasts to float32 per leaf before casting back.
The wire format is the concatenation of each leaf's native bytes; the
READER's template supplies dtypes/shapes.  Structural mismatches (a peer
running a different model or dtype — including same-byte-length
collisions) are detected via a per-publication ``tree_fingerprint``
carried on a ``<key>.fp`` side entry: the first mismatch logs one loud
ERROR naming the peer, after which the peer is skipped quietly until its
fingerprint matches again.  Payloads from pre-fingerprint publishers
(no ``.fp`` entry) fall back to the byte-length check alone.

Size: two transports, chosen per publication by payload size:

- **KV chunks** (small models, no shared-FS assumption): zlib-compressed
  native bytes, base64, chunked across KV entries with a meta entry written
  last as the commit point — model size bounded by coordinator memory, not
  the wire protocol's request-line cap.
- **Logdir binary side-channel** (``exchange_dir`` set and raw bytes ≥
  ``binary_threshold``): the flat native-dtype buffer is written to a
  sequence-numbered file in the shared run directory (the same shared-FS
  assumption checkpoints already make), committed by a KV pointer entry
  (``v2bin``) carrying length + CRC.  The coordinator socket then moves a
  ~60-byte pointer instead of gigabytes of base64 — this is what lets a
  100M+-parameter transformer exchange at disk bandwidth, matching the
  reference PS which moved full models every step (``distributed.py:145``).

Either way a torn read (meta/chunk/file mismatch while a peer republishes)
fails the checksum and that peer is skipped for the round; binary files are
sequence-numbered so a writer never truncates a file a reader may hold
open, and the last ``BINARY_GC_KEEP`` sequences are retained so a reader
whose pointer-fetch-to-file-read gap spans publish periods still finds its
file.  Skipped peers are counted (``fetch_skips``) and logged, so silent
participation loss is visible in worker output.
"""

from __future__ import annotations

import base64
import os
import zlib
from typing import Any

import jax
import numpy as np

KEY_FORMAT = "dtf/async_params/{}/task{}"
# Chunk size in base64 chars: comfortably under the coordinator's 8 MiB
# request-line cap and the client's initial response buffer.
CHUNK_CHARS = 512 * 1024
# Raw bytes at which publications switch to the binary side-channel (when
# the averager has an exchange_dir): past this, base64-through-one-socket
# is the bottleneck, not the model math.
BINARY_THRESHOLD_BYTES = 8 << 20
# Sequences of a task's binary files kept on disk; older ones are GC'd at
# publish time.  3 (current + two predecessors) tolerates a reader whose
# kv_get-to-read gap spans two publish periods on a slow shared FS.
BINARY_GC_KEEP = 3


def _leaf_meta(leaf) -> tuple[np.dtype, tuple, int]:
    """(dtype, shape, nbytes) without materializing device leaves."""
    dt = np.dtype(leaf.dtype) if hasattr(leaf, "dtype") else np.dtype(
        type(leaf))
    shape = tuple(getattr(leaf, "shape", ()))
    n = 1
    for s in shape:
        n *= int(s)
    return dt, shape, n * dt.itemsize


def tree_fingerprint(params: Any) -> str:
    """8-hex digest of the tree's per-leaf (dtype, shape) sequence.

    Carried in the publication meta so a peer running a different model or
    dtype (e.g. a mixed-version worker still publishing float32 of a bf16
    model) is diagnosed with one clear error instead of being silently
    byte-length-skipped every round (ADVICE r4).
    """
    metas = "|".join(f"{dt.str}{shape}"
                     for dt, shape, _ in map(_leaf_meta,
                                             jax.tree.leaves(params)))
    return format(zlib.crc32(metas.encode()), "08x")


def _flatten(params: Any) -> np.ndarray:
    """Concatenated native-dtype bytes of the tree's leaves (uint8)."""
    leaves = [np.ascontiguousarray(np.asarray(l))
              for l in jax.tree.leaves(params)]
    if not leaves:
        return np.zeros((0,), np.uint8)
    bufs = [l.reshape(-1).view(np.uint8) for l in leaves]
    if len(bufs) == 1:
        return bufs[0]  # GB-scale single-leaf trees skip the concat copy
    return np.concatenate(bufs)


def _unflatten(buf: np.ndarray, template: Any) -> Any | None:
    """Rebuild a tree shaped/typed like ``template`` from native bytes;
    None when the byte length doesn't match (peer published a different
    model/dtype — skip it)."""
    leaves, treedef = jax.tree.flatten(template)
    metas = [_leaf_meta(l) for l in leaves]
    if buf.nbytes != sum(m[2] for m in metas):
        return None
    out, pos = [], 0
    for dt, shape, nb in metas:
        out.append(buf[pos:pos + nb].view(dt).reshape(shape))
        pos += nb
    return jax.tree.unflatten(treedef, out)


def _encode_flat(flat: np.ndarray) -> str:
    return base64.b64encode(zlib.compress(flat.tobytes(), level=1)).decode()


def _encode(params: Any) -> str:
    return _encode_flat(_flatten(params))


def _decode(value: str, template: Any) -> Any | None:
    try:
        raw = zlib.decompress(base64.b64decode(value))
    except Exception:
        return None
    return _unflatten(np.frombuffer(raw, np.uint8), template)


def _mean_leaves(*xs):
    """Average in float32, return in the leaves' own dtype.  Accumulates
    in place (one f32 buffer) rather than stacking — at GB-scale trees a
    stack of N f32 upcasts would multiply peak host memory by N."""
    dt = xs[0].dtype
    acc = np.array(xs[0], np.float32)  # always a fresh buffer
    for x in xs[1:]:
        # Buffered mixed-dtype add: the ufunc streams the bf16->f32 cast
        # through cache-sized chunks instead of materializing another
        # full-size f32 temp per peer (~2x faster and allocation-stable
        # at GB-scale trees).
        np.add(acc, x, out=acc)
    acc /= len(xs)
    return acc.astype(dt)


def publish_chunked(coord, base_key: str, payload: str,
                    chunk_chars: int = CHUNK_CHARS, fp: str = "") -> int:
    """Write ``payload`` as ``<base>.c<i>`` chunks, then the ``<base>`` meta
    entry (``v1 <nchunks> <len> <crc32>``) as the commit point.  Returns the
    chunk count.

    ``fp`` (the publisher's ``tree_fingerprint``) rides a SEPARATE
    ``<base>.fp`` key, written before the meta commit point, NOT appended
    to the meta line: readers that predate the fingerprint parse the meta
    with strict field counts, and extending it would make every new
    publication unreadable to them — the rolling-upgrade scenario the
    fingerprint exists to diagnose."""
    nchunks = max(1, -(-len(payload) // chunk_chars))
    for i in range(nchunks):
        coord.kv_set(f"{base_key}.c{i}",
                     payload[i * chunk_chars:(i + 1) * chunk_chars])
    # Unconditional (empty fp clears a predecessor's entry): a stale .fp
    # left behind by an upgraded incarnation would otherwise permanently
    # exclude a downgraded-but-matching publisher.
    coord.kv_set(f"{base_key}.fp", fp)
    crc = zlib.crc32(payload.encode())
    coord.kv_set(base_key, f"v1 {nchunks} {len(payload)} {crc:08x}")
    return nchunks


def fetch_chunked(coord, base_key: str, meta: str | None = None
                  ) -> str | None:
    """Read a chunked payload; None when absent or torn (checksum/length
    mismatch against the meta entry).  ``meta``: the already-fetched meta
    entry, to save the extra coordinator round-trip."""
    if meta is None:
        meta = coord.kv_get(base_key)
    if meta is None:
        return None
    parts = meta.split()
    if len(parts) != 4 or parts[0] != "v1":
        return None
    try:
        nchunks, total, crc = int(parts[1]), int(parts[2]), int(parts[3], 16)
    except ValueError:
        return None
    chunks = []
    for i in range(nchunks):
        chunk = coord.kv_get(f"{base_key}.c{i}")
        if chunk is None:
            return None
        chunks.append(chunk)
    payload = "".join(chunks)
    if len(payload) != total or zlib.crc32(payload.encode()) != crc:
        return None
    return payload


def publish_binary(coord, base_key: str, flat: np.ndarray, exchange_dir: str,
                   task: int, seq: int,
                   gc_keep: int = BINARY_GC_KEEP, fp: str = "") -> str:
    """Write ``flat`` (native-dtype bytes, uint8) to
    ``<exchange_dir>/task{task}.{seq}.bin`` (atomic tmp+rename) and
    KV-commit a ``v2bin`` pointer with length + CRC (``fp`` rides the
    side ``<base>.fp`` key — see ``publish_chunked``).  Returns the file
    name.  The newest ``gc_keep`` sequences for this task survive; older
    files are garbage-collected — a reader holding a recent pointer can
    still finish its read even if it lags a couple of publish periods."""
    os.makedirs(exchange_dir, exist_ok=True)
    fname = f"task{task}.{seq}.bin"
    tmp = os.path.join(exchange_dir, fname + ".tmp")
    # No fsync: publications are throwaway state, not checkpoints.  The
    # close() below is what shared filesystems key visibility on
    # (close-to-open consistency), and the KV pointer's CRC rejects a
    # file whose data never survived a host crash — the reader skips that
    # peer for a round, which is this module's documented degradation
    # mode anyway.  An fsync here would serialize every publish on disk
    # bandwidth (~13 s/GB on a commodity disk) for durability nobody uses.
    with open(tmp, "wb") as fh:
        flat.tofile(fh)
    os.replace(tmp, os.path.join(exchange_dir, fname))
    coord.kv_set(f"{base_key}.fp", fp)  # unconditional — see publish_chunked
    crc = zlib.crc32(flat.data)
    coord.kv_set(base_key, f"v2bin {fname} {flat.nbytes} {crc:08x} {seq}")
    for old in os.listdir(exchange_dir):
        if not old.startswith(f"task{task}."):
            continue
        try:
            old_seq = int(old.split(".")[1])
        except (IndexError, ValueError):
            continue
        if old_seq <= seq - gc_keep:
            try:
                os.unlink(os.path.join(exchange_dir, old))
            except OSError:
                pass
    return fname


def fetch_binary(meta: str, exchange_dir: str) -> np.ndarray | None:
    """Resolve a ``v2bin`` pointer to its flat byte buffer (uint8); None
    when the file is missing/torn (length or CRC mismatch)."""
    parts = meta.split()
    if len(parts) != 5 or parts[0] != "v2bin":
        return None
    fname, nbytes, crc_hex = parts[1], parts[2], parts[3]
    if os.sep in fname or fname.startswith("."):
        return None  # pointer must stay inside the exchange dir
    path = os.path.join(exchange_dir, fname)
    try:
        flat = np.fromfile(path, np.uint8)
    except OSError:
        return None
    try:
        if flat.nbytes != int(nbytes) or zlib.crc32(flat.data) != int(
                crc_hex, 16):
            return None
    except ValueError:
        return None
    return flat


class ParamAverager:
    """Publish/average merged parameters through the coordination KV.

    ``namespace`` scopes the KV keys to one run (callers pass a digest of
    the run's logdir): a restarted worker of the SAME run rejoins its
    collective, while a fresh run against a still-running coordination
    service never adopts a dead run's weights.

    ``exchange_dir`` (usually ``<logdir>/async_exchange``) enables the
    binary side-channel for payloads of at least ``binary_threshold`` raw
    bytes; without it every publication rides the KV.  Readers handle both
    formats regardless — the WRITER's size decides the transport.

    Parameters keep their dtype end to end: a bf16 tree publishes bf16
    bytes (half the float32 volume) and the averaged result comes back
    bf16, with the mean computed in float32 per leaf.
    """

    def __init__(self, coord, task_index: int, num_workers: int,
                 namespace: str = "default",
                 exchange_dir: str | None = None,
                 binary_threshold: int = BINARY_THRESHOLD_BYTES,
                 print_fn=print):
        self._coord = coord
        self._task = task_index
        self._num_workers = num_workers
        self._ns = namespace
        self._dir = exchange_dir
        self._threshold = binary_threshold
        self._print = print_fn
        # Resume the sequence from files a previous incarnation left behind:
        # a restart starting over at 0 would strand the old high-sequence
        # files (model-size each) outside GC's reach for ~500 periods.
        self._seq = 0
        if exchange_dir is not None and os.path.isdir(exchange_dir):
            prefix = f"task{task_index}."
            for f in os.listdir(exchange_dir):
                if f.startswith(prefix) and f.endswith(".bin"):
                    try:
                        self._seq = max(self._seq, int(f.split(".")[1]))
                    except (IndexError, ValueError):
                        pass
        #: transport and MB/s of the last publish (observability/bench)
        self.last_publish_transport = ""
        self.last_publish_mb_per_sec = 0.0
        #: per-peer count of rounds skipped on a torn/missing payload —
        #: persistent skipping (ADVICE r3) shows up here and in the log
        self.fetch_skips: dict[int, int] = {}
        # Peers already diagnosed with a tree-fingerprint mismatch: the
        # structural error prints ONCE per peer (it will never heal on its
        # own), then the peer is skipped quietly.
        self._fp_mismatch_reported: set[int] = set()

    def _key(self, task: int) -> str:
        return KEY_FORMAT.format(self._ns, task)

    def _publish(self, host_merged: Any, fp: str | None = None) -> None:
        import time
        flat = _flatten(host_merged)
        if fp is None:
            fp = tree_fingerprint(host_merged)
        t0 = time.perf_counter()
        if self._dir is not None and flat.nbytes >= self._threshold:
            self._seq += 1
            publish_binary(self._coord, self._key(self._task), flat,
                           self._dir, self._task, self._seq, fp=fp)
            self.last_publish_transport = "binary"
        else:
            publish_chunked(self._coord, self._key(self._task),
                            _encode_flat(flat), fp=fp)
            self.last_publish_transport = "kv"
        dt = time.perf_counter() - t0
        self.last_publish_mb_per_sec = (flat.nbytes / 1e6 / dt) if dt else 0.0

    def _fetch_peer(self, task: int, template: Any,
                    my_fp: str | None = None) -> Any | None:
        meta = self._coord.kv_get(self._key(task))
        if meta is None:
            return None  # peer hasn't published yet — normal, not a skip
        peer_fp = self._coord.kv_get(self._key(task) + ".fp")
        if peer_fp:  # empty/absent -> pre-fingerprint publisher, no check
            mine = my_fp if my_fp is not None else tree_fingerprint(template)
            if peer_fp != mine:
                # Structural mismatch (different model or dtype on the
                # wire): a torn read heals next round, this doesn't — say
                # so loudly ONCE per mismatch episode, then skip quietly.
                if task not in self._fp_mismatch_reported:
                    self._fp_mismatch_reported.add(task)
                    self._print(
                        f"[param_sync] ERROR: peer {task} publishes a "
                        f"different parameter tree (fingerprint {peer_fp} "
                        f"vs local {mine}) — mixed model/dtype versions in "
                        f"one run; this peer will be excluded from "
                        f"averaging until it matches")
                self.fetch_skips[task] = self.fetch_skips.get(task, 0) + 1
                return None
            # Healed (restarted with the right model): arm the one-time
            # error again so a LATER mismatch is a new loud episode.
            self._fp_mismatch_reported.discard(task)
        if meta.startswith("v2bin"):
            if self._dir is None:
                peer = None
            else:
                flat = fetch_binary(meta, self._dir)
                peer = None if flat is None else _unflatten(flat, template)
        else:
            value = fetch_chunked(self._coord, self._key(task), meta=meta)
            peer = None if value is None else _decode(value, template)
        if peer is None:
            # Published but unreadable (torn mid-republish, GC'd file,
            # shape/dtype mismatch): count and say so — persistent skipping
            # quietly shrinks averaging participation otherwise.
            n = self.fetch_skips.get(task, 0) + 1
            self.fetch_skips[task] = n
            self._print(f"[param_sync] task {self._task}: skipping peer "
                        f"{task} this round (unreadable payload, "
                        f"{n} skips total)")
        return peer

    def exchange(self, merged: Any, alive=None) -> tuple[Any, int]:
        """Publish ``merged`` (host-side average of local replicas), pull
        live peers' publications, and return
        ``(averaged_params, num_peers_included)``.

        Peers that haven't published yet (slower cadence, just restarted)
        are simply absent — nobody blocks; that IS the async contract.
        ``alive`` (per-task liveness bits from the heartbeat health cache)
        excludes dead/finished peers, whose frozen snapshots would otherwise
        anchor the average forever.
        """
        host_merged = jax.tree.map(
            lambda x: np.ascontiguousarray(np.asarray(x)), merged)
        my_fp = tree_fingerprint(host_merged)
        self._publish(host_merged, fp=my_fp)
        contributions = [host_merged]
        for task in range(self._num_workers):
            if task == self._task:
                continue
            if alive is not None and task < len(alive) and not alive[task]:
                continue
            peer = self._fetch_peer(task, host_merged, my_fp=my_fp)
            if peer is not None:
                contributions.append(peer)
        n = len(contributions)
        if n == 1:
            return merged, 0
        avg = jax.tree.map(_mean_leaves, *contributions)
        return avg, n - 1

    def pull_latest(self, template: Any) -> Any | None:
        """Average of everything published in this run's namespace
        (restart-and-rejoin: a rejoining worker adopts the collective's
        state instead of step 1 — stale entries are exactly the durability
        this provides, so liveness is deliberately NOT checked here)."""
        my_fp = tree_fingerprint(template)
        contributions = []
        for task in range(self._num_workers):
            peer = self._fetch_peer(task, template, my_fp=my_fp)
            if peer is not None:
                contributions.append(peer)
        if not contributions:
            return None
        return jax.tree.map(_mean_leaves, *contributions)


class OverlappedAverager:
    """Background-threaded parameter exchange — the GB-scale publish/
    fetch/average runs CONCURRENTLY with training instead of stalling it
    (VERDICT r4 #5: a 1.1 GB / 2-peer exchange measured 36 s of
    stop-the-world pause per sync period; the reference PS moved
    parameters concurrently with other workers' compute every step,
    ``distributed.py:145``).

    Protocol (delayed averaging with delta correction):

    - at each sync period the trainer hands over a host SNAPSHOT of its
      merged params and immediately keeps training;
    - the worker thread publishes the snapshot, fetches live peers, and
      averages — all while local steps continue;
    - at the NEXT period the trainer collects the finished average and
      applies it as a DELTA against the snapshot it came from
      (``params += avg - snapshot``): the consensus pull lands one
      period late, but the K local steps taken meanwhile are preserved
      instead of overwritten (plain stale adoption would silently undo
      them — that is the difference between "delayed averaging" and
      "losing a period of work").

    Equivalence: with the delta applied, the update at period n is
    exactly the synchronous exchange's update computed from period
    n-1's parameters — the same math one period stale, which is inside
    the bounded-staleness contract async mode already documents (peers
    read whatever publications exist; nobody waits).  Pinned by
    ``tests/test_param_sync.py::test_overlapped_matches_one_period_stale_sync``.

    One exchange is in flight at a time; if the previous one has not
    finished by the next period, the trainer simply keeps training and
    retries collection a period later (the exchange thread never blocks
    the step loop — that is the whole point).
    """

    def __init__(self, averager: ParamAverager, alive_fn=None,
                 print_fn=print):
        import queue
        import threading
        self._avg = averager
        self._alive_fn = alive_fn
        self._print = print_fn
        self._in: "queue.Queue" = queue.Queue(maxsize=1)
        self._out: "queue.Queue" = queue.Queue(maxsize=1)
        self._busy = False
        self._closed = False
        #: wall seconds the last background exchange took (observability)
        self.last_exchange_seconds = 0.0
        self.exchanges_completed = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="param-exchange")
        self._thread.start()

    def _loop(self):
        import queue
        import time
        while True:
            snapshot = self._in.get()
            if snapshot is None or self._closed:
                return
            t0 = time.perf_counter()
            try:
                alive = self._alive_fn() if self._alive_fn else None
                avg, peers = self._avg.exchange(snapshot, alive=alive)
            except Exception as e:
                # Control-plane hiccups (a peer evicted mid-exchange, an
                # unreachable coordinator) must not kill the thread; report
                # a no-op result so the trainer just continues.
                self._print(f"[param_sync] background exchange failed "
                            f"({type(e).__name__}: {e}); skipping period")
                avg, peers = snapshot, 0
            self.last_exchange_seconds = time.perf_counter() - t0
            if self._closed:
                return  # nobody will collect; exit instead of blocking
            try:
                self._out.put_nowait((avg, snapshot, peers))
            except queue.Full:  # pragma: no cover — busy-flag protocol
                pass            # prevents this; defensive against a leak

    @property
    def busy(self) -> bool:
        """True while an exchange is in flight AND its result has not
        been collected yet.  Callers should check this BEFORE
        materializing a snapshot — a device-to-host copy of a GB tree
        that ``submit`` would refuse is exactly the stall this class
        exists to hide."""
        return self._busy

    def poll(self) -> tuple[Any, Any, int] | None:
        """Collect the finished exchange, if any: ``(avg, snapshot,
        peers)`` — apply ``params += avg - snapshot`` when ``peers > 0``
        — or None while still in flight / nothing launched."""
        import queue
        if not self._busy:
            return None
        try:
            result = self._out.get_nowait()
        except queue.Empty:
            self._print("[param_sync] background exchange still in "
                        "flight; continuing to train (will collect "
                        "next period)")
            return None
        self._busy = False
        self.exchanges_completed += 1
        return result

    def submit(self, merged_host: Any) -> bool:
        """Launch the next background exchange with this host snapshot;
        False (snapshot unused) when one is already in flight."""
        if self._busy:
            return False
        self._in.put(merged_host)
        self._busy = True
        return True

    def step_period(self, merged_host: Any) -> tuple[Any, Any, int] | None:
        """poll() + submit() in one call, for callers whose snapshot is
        already host-side (tests, the bench overlap arm)."""
        result = self.poll()
        self.submit(merged_host)
        return result

    def drain(self, timeout: float | None = None):
        """Block for the in-flight exchange (end of training / tests).
        Returns the final ``(avg, snapshot, peers)`` or None."""
        import queue
        if not self._busy:
            return None
        try:
            result = self._out.get(timeout=timeout)
        except queue.Empty:
            return None
        self._busy = False
        self.exchanges_completed += 1
        return result

    def close(self, timeout: float = 30.0) -> bool:
        """Stop the worker thread and JOIN it.  Safe while an exchange is
        in flight (a peer evicted mid-exchange leaves the thread inside
        the coordination client's retry budget — it finishes or no-ops,
        sees the closed flag, and exits); the sentinel is delivered
        without blocking even if a snapshot is still queued.  Returns
        True when the thread is confirmed dead — the regression surface
        for the thread-leak bug where close() neither joined nor could
        outlive a full input queue."""
        import queue
        self._closed = True
        try:
            self._in.put_nowait(None)
        except queue.Full:
            pass  # worker is mid-get; it checks _closed on its next loop
        self._thread.join(timeout)
        return not self._thread.is_alive()


def run_namespace(logdir: str) -> str:
    """Stable per-run KV namespace: a digest of the run's logdir (shared by
    all of the run's workers and its restarts; different for fresh runs)."""
    import os
    import zlib as _zlib
    return format(_zlib.crc32(os.path.abspath(logdir).encode()), "08x")
