"""Python bindings for the C++ coordination service (N1 control plane).

The native library (``distributed_tensorflow_tpu/csrc/coordination/coord.cc``; the repo-root ``src`` symlink keeps the short path) provides task registration
with incarnation numbers, named barriers, heartbeat health tracking, and a KV
store — the control-plane residue of the reference's gRPC runtime
(``tf.train.Server``, reference ``distributed.py:54``) once the data plane has
moved onto ICI collectives.

Bindings use ctypes against a C ABI (no pybind11 in the image).  The shared
library is built on first use with ``g++`` from the in-tree source; build
artifacts are cached next to this file.
"""

from __future__ import annotations

import ctypes
import json
import os
import random
import threading
import time
import zlib

from ..utils import faults, tracing

_LIB_NAME = "libdtfcoord.so"
_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.normpath(
    os.path.join(_HERE, "..", "csrc", "coordination", "coord.cc"))

_lib = None
_lib_lock = threading.Lock()


def _load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        override = os.environ.get("DTF_COORD_BIN")
        if override:
            # Alternate prebuilt library (docs/static_analysis.md,
            # "Sanitizer builds"): `make -C csrc/coordination tsan` then
            # DTF_COORD_BIN=<...>/libdtfcoord.tsan.so runs every
            # coordination test against the instrumented binary
            # (sanitized builds additionally need the matching
            # LD_PRELOAD, e.g. $(g++ -print-file-name=libtsan.so)).
            lib = ctypes.CDLL(override)
        else:
            from ..utils.native import build_and_load
            lib = build_and_load(os.path.join(_HERE, _LIB_NAME), _SRC,
                                 extra_flags=("-pthread",))
        lib.dtf_coord_server_start.restype = ctypes.c_void_p
        lib.dtf_coord_server_start.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_char_p]
        lib.dtf_coord_server_port.restype = ctypes.c_int
        lib.dtf_coord_server_port.argtypes = [ctypes.c_void_p]
        lib.dtf_coord_server_stop.argtypes = [ctypes.c_void_p]
        lib.dtf_coord_server_join.argtypes = [ctypes.c_void_p]
        try:
            lib.dtf_coord_server_start2.restype = ctypes.c_void_p
            lib.dtf_coord_server_start2.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_double,
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
            lib.dtf_coord_server_set_shard.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        except AttributeError:
            # A prebuilt DTF_COORD_BIN older than the sharded plane: the
            # single-instance path still works; shard identity is
            # best-effort.
            pass
        try:
            lib.dtf_coord_server_start3.restype = ctypes.c_void_p
            lib.dtf_coord_server_start3.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_double,
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_double, ctypes.c_char_p]
        except AttributeError:
            # Prebuilt DTF_COORD_BIN older than coordinator HA: primaries
            # still work; standby_of raises at construction.
            pass
        lib.dtf_coord_client_create.restype = ctypes.c_void_p
        lib.dtf_coord_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.dtf_coord_client_destroy.argtypes = [ctypes.c_void_p]
        lib.dtf_coord_client_request.restype = ctypes.c_int
        lib.dtf_coord_client_request.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_double]
        _lib = lib
        return _lib


class CoordinationError(RuntimeError):
    """Base class for control-plane failures (protocol ERRs, timeouts)."""


class CoordinationTransportError(CoordinationError):
    """A transport-level failure (connect/send/recv) that survived the
    client's whole retry budget — the socket stayed dead through the
    jittered-backoff reconnect attempts.  Callers that can degrade
    gracefully (health polling, async exchange) catch the base class;
    callers that must distinguish a dead coordinator from a protocol
    error can catch this one."""


class CoordinationBackgroundError(CoordinationError):
    """A client background thread (heartbeats, health polling) died on an
    unexpected exception.  The thread latches the error instead of dying
    silently — a worker whose heartbeats stopped would otherwise only
    learn of it when the cluster evicts it — and the next protocol call
    on the owning client re-raises it here."""


class CoordinationServer:
    """Hosts the control-plane service — the PS role's surviving duty.

    ``persist_path`` (optional) journals the KV store to that file and
    restores it on construction, so a restarted coordination service keeps
    async-published parameters and signalling state (the durability the
    reference's PS provided by surviving its workers, SURVEY §5).

    ``standby_of`` (optional, ``"host:port"``) starts this instance as a
    warm STANDBY of that control shard (docs/fault_tolerance.md,
    "Coordinator HA"): it snapshot-bootstraps via ``REPLJOIN``, applies
    the primary's journal stream (``REPLSTREAM``), refuses mutating
    commands with ``NOTPRIMARY``, and promotes itself — coordinator
    generation bump, persisted when a persist path is set — after
    ``lease_timeout`` seconds without primary contact.
    """

    def __init__(self, port: int, num_tasks: int,
                 heartbeat_timeout: float = 10.0,
                 persist_path: str | None = None,
                 shard: int = 0, nshards: int = 1,
                 standby_of: str | None = None,
                 lease_timeout: float = 2.0,
                 advertise_addr: str | None = None):
        self._lib = _load_library()
        if persist_path:
            os.makedirs(os.path.dirname(os.path.abspath(persist_path)),
                        exist_ok=True)
        encoded = persist_path.encode() if persist_path else None
        if standby_of and not hasattr(self._lib,
                                      "dtf_coord_server_start3"):
            raise CoordinationError(
                "this libdtfcoord build predates coordinator HA — rebuild "
                "it (or drop the DTF_COORD_BIN override) to run a standby")
        if hasattr(self._lib, "dtf_coord_server_start3"):
            # Role travels through construction exactly like shard
            # identity below: a standby must never answer its first
            # request as a primary.
            # advertise_addr is how PEER standbys reach this one at
            # promotion time (probed so a survivor adopts an already-
            # promoted peer instead of promoting a second primary);
            # None -> the C++ default, loopback + the bound port.
            self._handle = self._lib.dtf_coord_server_start3(
                port, num_tasks, heartbeat_timeout, encoded, shard,
                nshards, standby_of.encode() if standby_of else None,
                lease_timeout,
                advertise_addr.encode() if advertise_addr else None)
        elif hasattr(self._lib, "dtf_coord_server_start2"):
            # Shard identity of a sharded coordination plane (SHARDINFO;
            # docs/param_exchange.md "Hierarchical exchange") travels
            # through construction, so it is fixed BEFORE the accept
            # thread takes its first connection — a bring-up probe racing
            # a fixed-port launch can never read the default identity.
            self._handle = self._lib.dtf_coord_server_start2(
                port, num_tasks, heartbeat_timeout, encoded, shard,
                nshards)
        else:
            # Prebuilt DTF_COORD_BIN older than the sharded plane.
            self._handle = self._lib.dtf_coord_server_start(
                port, num_tasks, heartbeat_timeout, encoded)
        self.shard = shard
        self.nshards = nshards
        self.standby_of = standby_of
        self.lease_timeout = lease_timeout
        self._started = False

    def start(self) -> None:
        if not self._handle:
            raise CoordinationError("coordination server failed to bind")
        self._started = True

    @property
    def port(self) -> int:
        return self._lib.dtf_coord_server_port(self._handle)

    def join(self) -> None:
        """Block serving forever (``server.join()`` parity, ``distributed.py:55-56``)."""
        self._lib.dtf_coord_server_join(self._handle)

    def stop(self) -> None:
        if self._handle:
            self._lib.dtf_coord_server_stop(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


def _parse_endpoints(spec) -> list[tuple[str, int]]:
    """``"h1:p1,h2:p2"`` (or an iterable of the same / of tuples) ->
    ordered ``(host, port)`` list."""
    if spec is None:
        return []
    if isinstance(spec, str):
        spec = [a for a in spec.split(",") if a]
    out: list[tuple[str, int]] = []
    for addr in spec:
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            out.append((host, int(port)))
        else:
            out.append((addr[0], int(addr[1])))
    return out


def parse_standby_map(spec) -> dict[int, str]:
    """``--coord_standbys`` spec -> ``{instance_index: "host:port[,...]"}``.

    Two forms (docs/fault_tolerance.md, "KV-shard HA"):

    * ``"h:p[,h:p...]"`` — a plain endpoint list: standbys of the CONTROL
      shard only (instance 0), the PR-15 flat form.
    * ``"0:h:p[,h:p];1:h:p[,...]"`` — a per-instance map: each
      ``;``-separated segment is ``<instance>:<comma endpoint list>`` and
      wires that instance's ordered warm-standby list, so every KV shard
      of a sharded plane can carry its own replica set.

    A dict (``{0: "h:p", 1: "h:p"}``) passes through normalized.  A
    segment is map-form iff its first ``:``-field is all digits and the
    remainder still contains a ``:`` — ``"0:host:2222"`` is instance 0,
    ``"host:2222"`` is the flat form.
    """
    if not spec:
        return {}
    if isinstance(spec, dict):
        return {int(k): v for k, v in spec.items() if v}
    segments = [s for s in str(spec).split(";") if s]
    out: dict[int, str] = {}
    for seg in segments:
        idx, _, rest = seg.partition(":")
        if idx.isdigit() and ":" in rest:
            if int(idx) in out:
                raise ValueError(
                    f"duplicate instance {idx} in standby map {spec!r}")
            out[int(idx)] = rest
        elif len(segments) == 1:
            out[0] = seg  # flat form: control-shard standbys
        else:
            raise ValueError(
                f"malformed standby map segment {seg!r} in {spec!r} "
                "(want '<instance>:host:port[,host:port...]')")
    return out


def _fnv1a(data: str) -> str:
    """FNV-1a 32-bit hex — the replication wire checksum (mirror of
    ``Fnv1a`` in coord.cc)."""
    h = 0x811C9DC5
    for b in data.encode():
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return f"{h:08x}"


class CoordinationClient:
    """Per-task client: register, barrier, heartbeat, KV, health.

    Transport failures retry transparently: every protocol request is an
    idempotent one-shot line over a fresh connection, so a dropped/reset
    socket is retried with jittered exponential backoff (base
    ``retry_base``, doubling to ``retry_max_interval``) until
    ``retry_budget`` seconds have elapsed, then raises
    :class:`CoordinationTransportError`.  A transient coordinator outage
    (restart, network blip, injected chaos) thus becomes a stall, not a
    crash — the reference's ``recovery_wait_secs`` poll made survivable
    (``distributed.py:111,125``).  Liveness-cadence requests (register
    polls, heartbeats) opt out with ``retry_budget=0``: their own cadence
    IS the retry.

    **Coordinator HA** (docs/fault_tolerance.md, "Coordinator HA"): the
    client holds an ORDERED endpoint list — ``host`` may be a
    comma-separated ``"h1:p1,h2:p2"`` spec, and/or ``standbys`` appends
    warm-standby endpoints.  The same retry loop walks the list on a
    transport error or a ``NOTPRIMARY <leader>`` redirect (redirects cost
    no backoff), re-resolving leadership without losing a call's nonce
    semantics.  Every reply carries a generation/role trailer; once a
    coordinator generation G has been seen, replies stamped < G are
    fenced — a promoted-then-restarted old primary can never win a write
    back (the split-brain fence).  The first success after an outage
    whose generation moved forward emits one ``kind="recovery"``
    ``action="coord_failover"`` record carrying the worker-visible gap —
    or ``action="kv_shard_failover"`` (plus the shard id) when
    ``failover_shard`` names this client as a KV data shard of a sharded
    plane (docs/fault_tolerance.md, "KV-shard HA").
    """

    def __init__(self, host: str, port: int, task_id: int,
                 incarnation: int | None = None,
                 retry_budget: float = 6.0,
                 retry_base: float = 0.05,
                 retry_max_interval: float = 1.0,
                 standbys=None,
                 failover_shard: int | None = None):
        self._lib = _load_library()
        if "," in host or ":" in host:
            # "h1:p1[,h2:p2...]" spec (the observer/endpoint-list form);
            # port is ignored — each entry carries its own.
            self._endpoints = _parse_endpoints(host)
        else:
            self._endpoints = [(host, int(port))]
        self._endpoints += _parse_endpoints(standbys)
        # Eager handle creation (no I/O happens until a request), so the
        # heartbeat/health threads never race a lazy construction.
        self._handles = [
            self._lib.dtf_coord_client_create(h.encode(), p, task_id)
            for h, p in self._endpoints]
        self._active = 0
        self.task_id = task_id
        self.incarnation = incarnation if incarnation is not None else time.time_ns()
        self.restarts = 0
        self._registered = False  # set by register(); gates leave()
        self._retry_budget = float(retry_budget)
        self._retry_base = float(retry_base)
        self._retry_max_interval = float(retry_max_interval)
        # Deterministic per-task jitter: reproducible chaos runs, and peers
        # still desynchronize their retry storms against each other.
        self._retry_rng = random.Random(0x9E3779B1 * (task_id + 1))
        self._heartbeat_thread: threading.Thread | None = None
        self._heartbeat_stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self._cached_health: list[bool] = []
        self._health_lock = threading.Lock()
        self._progress_step = -1  # latest step to carry in heartbeats
        self._telemetry = None    # optional Telemetry bus (attach_telemetry)
        # Latched background-thread failure: (thread name, exception).  Set
        # by the heartbeat/health loops on a non-CoordinationError crash,
        # re-raised as CoordinationBackgroundError on the next client call.
        self._background_error: tuple[str, BaseException] | None = None
        # Coordinator-generation tracking (guarded by _gen_lock: the
        # heartbeat/health threads issue requests concurrently with the
        # caller).  last_generation/last_role mirror the newest reply
        # trailer; _max_generation is the fence; _outage_started stamps
        # the first failure of the current outage so the eventual success
        # can report the worker-visible gap.
        self._gen_lock = threading.Lock()
        self.last_generation = 0
        self.last_role: str | None = None
        self._max_generation = 0
        self._gen_seeded = len(self._endpoints) < 2
        self._outage_started: float | None = None
        self._outage_gen = 0
        # KV-shard identity for failover telemetry: None -> this client
        # talks to the control shard (action="coord_failover"); an int ->
        # a KV data shard of a sharded plane (action="kv_shard_failover"
        # stamped with the shard id).  Set by CoordinationRouter.
        self._failover_shard = failover_shard
        #: failovers this client has ridden (generation moved forward
        #: across an outage) — counted whether or not telemetry is
        #: attached.  ``param_sync`` polls this (via
        #: :meth:`plane_failovers`) to trigger its post-failover replay of
        #: write-once records a dead primary may have acknowledged but
        #: never replicated.
        self.failover_count = 0

    @classmethod
    def observer(cls, host: str, port: int = 0,
                 retry_budget: float = 2.0) -> "CoordinationClient":
        """A pure-observer client (task_id -1): it never registers, so it
        can never shrink a live cluster's elastic membership — the
        constructor ``tools/watch_run.py`` and the serving tier's
        checkpoint watcher share.  ``host`` may be a comma-separated
        endpoint list (primary first, then standbys)."""
        return cls(host, port, task_id=-1, retry_budget=retry_budget)

    def _latch_background_error(self, thread_name: str,
                                exc: BaseException) -> None:
        if self._background_error is None:
            self._background_error = (thread_name, exc)

    def check_background(self) -> None:
        """Raise :class:`CoordinationBackgroundError` if a background
        thread (heartbeats, health polling) has died on an unexpected
        exception.  Every protocol request calls this implicitly; loops
        whose hot path makes no protocol calls (the masked-sync mask reads
        only cached snapshots) must call it explicitly — a worker whose
        heartbeats silently stopped is a zombie awaiting eviction."""
        if self._background_error is not None:
            name, exc = self._background_error
            raise CoordinationBackgroundError(
                f"coordination client {name} thread died: "
                f"{type(exc).__name__}: {exc}") from exc

    def _seed_generation_fence(self) -> None:
        """One-shot, before this client's FIRST request on a multi-endpoint
        list: best-effort probe of every endpoint's generation (INFO, short
        timeout, failures ignored) so ``_max_generation`` starts at the
        cluster's real maximum.  Without this, a FRESH client — a restarted
        worker — whose list leads with a resurrected pre-promotion primary
        would accept the ghost wholesale (its replies carry the highest
        generation the client has ever seen) and split the brain the fence
        exists to prevent; the ghost answers happily, so only comparing it
        against the other endpoints can unmask it."""
        best_gen, best_idx = 0, None
        for i in range(len(self._endpoints)):
            once = self._request_once("INFO", 0.5, 1 << 14, index=i)
            if once is None:
                continue
            _, gen, role = once
            if gen > best_gen:
                best_gen, best_idx = gen, i
        with self._gen_lock:
            if best_gen > self._max_generation:
                self._max_generation = best_gen
        if best_idx is not None and best_idx != self._active:
            self._active = best_idx

    def _request_once(self, line: str, timeout: float, bufsize: int,
                      index: int | None = None
                      ) -> tuple[str, int, str | None] | None:
        """One wire attempt against the ACTIVE endpoint (or an explicit
        ``index``); None on transport failure, else ``(response,
        generation, role)`` with the server's 0x1f generation/role trailer
        split off the response body."""
        handle = self._handles[self._active if index is None else index]
        raw = None
        while True:
            buf = ctypes.create_string_buffer(bufsize)
            n = self._lib.dtf_coord_client_request(
                handle, line.encode(), buf, bufsize, timeout)
            if n < 0:
                return None
            if n < bufsize - 1:
                raw = buf.value.decode()
                break
            # Truncated: re-issue with a buffer sized to the full response
            # (requests are idempotent one-shot lines).
            bufsize = n + 2
        gen, role = 0, None
        cut = raw.rfind("\x1f")
        if cut >= 0 and raw.startswith("gen=", cut + 1):
            meta, raw = raw[cut + 1:], raw[:cut]
            for part in meta.split():
                key, _, value = part.partition("=")
                if key == "gen":
                    try:
                        gen = int(value)
                    except ValueError:
                        gen = 0
                elif key == "role":
                    role = value
        return raw, gen, role

    def _endpoint_index(self, addr: str) -> int | None:
        """Index of a ``host:port`` leader hint in the endpoint list (None
        when the hint is absent/unknown — round-robin takes over)."""
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            return None
        port_num = int(port)
        local = {"localhost", "127.0.0.1"}
        for i, (h, p) in enumerate(self._endpoints):
            if p != port_num:
                continue
            if h == host or (h in local and host in local):
                return i
        return None

    def _note_failure(self) -> None:
        """Stamp the start of an outage (first failure wins) so the
        eventual success can report the worker-visible gap."""
        with self._gen_lock:
            if self._outage_started is None:
                self._outage_started = time.monotonic()
                self._outage_gen = self._max_generation

    def _note_success(self, gen: int, role: str | None) -> None:
        """Record the reply trailer; when this success ends an outage AND
        the coordinator generation moved forward, the stall was a
        failover — emit the ``coord_failover`` (control shard) or
        ``kv_shard_failover`` (KV data shard, with the shard id) recovery
        record carrying the worker-visible gap (the acceptance budget:
        <= 2x the leadership lease timeout)."""
        failover = None
        with self._gen_lock:
            self.last_generation = gen
            self.last_role = role
            if gen > self._max_generation:
                self._max_generation = gen
            if self._outage_started is not None:
                gap = time.monotonic() - self._outage_started
                if gen > self._outage_gen:
                    failover = (gap, gen)
                    self.failover_count += 1
                self._outage_started = None
        if failover is not None and self._telemetry is not None:
            gap, gen = failover
            host, port = self._endpoints[self._active]
            if self._failover_shard is None:
                self._telemetry.counter("coord_failovers").inc()
                self._telemetry.emit(
                    "recovery", step=max(self._progress_step, 0),
                    action="coord_failover", gap_s=round(gap, 3),
                    generation=gen, endpoint=f"{host}:{port}")
            else:
                self._telemetry.counter("kv_shard_failovers").inc()
                self._telemetry.emit(
                    "recovery", step=max(self._progress_step, 0),
                    action="kv_shard_failover", gap_s=round(gap, 3),
                    generation=gen, endpoint=f"{host}:{port}",
                    shard=self._failover_shard)

    def _request(self, line: str, timeout: float = 5.0,
                 bufsize: int = 1 << 20,
                 retry_budget: float | None = None) -> str:
        self.check_background()
        seed = False
        with self._gen_lock:
            if not self._gen_seeded:
                self._gen_seeded = True
                seed = True
        if seed:
            self._seed_generation_fence()
        budget = self._retry_budget if retry_budget is None else retry_budget
        command = line.split(None, 1)[0] if line else ""
        deadline = time.monotonic() + budget
        delay = self._retry_base
        attempts = 0
        redirects = 0
        refusal = ""
        t0_unix, t0_perf = time.time(), time.perf_counter()
        while True:
            injector = faults.active()
            fault = (injector.coordination_fault(command)
                     if injector is not None else None)
            if fault is not None and fault[0] == "delay":
                time.sleep(fault[1])
                fault = None
            if fault is not None and fault[0] == "drop":
                once = None  # injected transport failure
            else:
                # Generation guard: stamp the request with the highest
                # coordinator generation this client has seen, so a stale
                # ghost (a restarted pre-promotion primary) refuses the
                # command WITHOUT executing it — the server-side half of
                # the split-brain fence.  Recomputed per attempt: the
                # fence tightens mid-walk as newer generations appear.
                seen = self._max_generation
                wire = f"gen={seen} {line}" if seen > 0 else line
                once = self._request_once(wire, timeout, bufsize)
            resp = None
            leader_idx = None
            walk = once is None  # plain transport failure: round-robin
            if once is not None:
                body, gen, role = once
                if body.startswith("NOTPRIMARY"):
                    # A standby (or demoted primary) refused and named its
                    # leader: walk the endpoint list toward it.  Not an
                    # answer — the call keeps its line (and nonce) intact.
                    parts = body.split()
                    if len(parts) > 1:
                        leader_idx = self._endpoint_index(parts[1])
                    refusal = ", last refusal NOTPRIMARY"
                    walk = True
                elif 0 < gen < self._max_generation:
                    # Stale primary: an older generation's ghost came back
                    # (a restarted pre-promotion primary).  Fence it —
                    # accepting its answer (worse: landing a write on it)
                    # would split the brain the promotion just healed.
                    refusal = ", last refusal stale generation"
                    walk = True
                else:
                    resp = body
            if resp is not None:
                self._note_success(gen, role)
                if attempts and self._telemetry is not None:
                    # The recovery itself is telemetry: one record naming
                    # the action, not one per retry (counters carry those).
                    self._telemetry.emit(
                        "recovery", step=max(self._progress_step, 0),
                        action="request_retry", command=command,
                        attempts=attempts)
                tracer = tracing.active()
                if tracer is not None:
                    # Control-plane spans: every request (retries included)
                    # becomes one span in the exported cross-worker trace,
                    # so a slow/stormy coordinator shows up as trace rows,
                    # not just as mystery step-time (docs/observability.md).
                    tracer.emit_span(
                        f"coord.{command.lower()}", t0_unix,
                        (time.perf_counter() - t0_perf) * 1000.0,
                        attempts=attempts)
                return resp
            # Failure: stamp the outage and advance the endpoint BEFORE
            # the deadline check, so even budget-0 callers (heartbeats)
            # leave the pointer on the next candidate for whoever calls
            # next.
            self._note_failure()
            if walk and len(self._endpoints) > 1:
                if leader_idx is not None and leader_idx != self._active:
                    self._active = leader_idx
                else:
                    self._active = (self._active + 1) % len(self._endpoints)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise CoordinationTransportError(
                    f"coordination request failed: {command} "
                    f"({attempts + 1} attempt(s), retry budget {budget}s"
                    f"{refusal})")
            attempts += 1
            if self._telemetry is not None:
                self._telemetry.counter("coordination_retries").inc()
            if once is not None and redirects < len(self._endpoints):
                # A NOTPRIMARY/stale refusal came from a LIVE server: the
                # next endpoint is a different process, so walking on
                # costs no backoff — one free pass around the list, then
                # the normal jittered backoff paces the search for a
                # promotion still in flight.
                redirects += 1
                continue
            # Jittered exponential backoff (0.5-1.5x the nominal delay),
            # capped by the budget remainder.  Sleeping on the stop event
            # makes close() abort an in-flight retry loop promptly.
            sleep_for = min(delay * (0.5 + self._retry_rng.random()),
                            remaining)
            if self._heartbeat_stop.wait(max(sleep_for, 0.0)):
                raise CoordinationTransportError(
                    f"coordination request aborted by close(): {command}")
            delay = min(delay * 2.0, self._retry_max_interval)

    def register(self, timeout: float = 60.0, poll_interval: float = 1.0) -> int:
        """Register with poll-until-ready semantics (``recovery_wait_secs``-style,
        reference ``distributed.py:111,125``).  Returns the restart count the
        server has seen for this task id (>0 ⇒ we are a rejoining incarnation).
        """
        deadline = time.monotonic() + timeout
        while True:
            try:
                # retry_budget=0: this poll loop IS the retry policy.
                resp = self._request(
                    f"REGISTER {self.task_id} {self.incarnation}",
                    retry_budget=0.0)
                if resp.startswith("OK"):
                    for part in resp.split():
                        if part.startswith("restarts="):
                            self.restarts = int(part.split("=", 1)[1])
                    self._registered = True
                    return self.restarts
            except CoordinationError:
                pass
            if time.monotonic() >= deadline:
                raise CoordinationError("register timed out waiting for coordinator")
            time.sleep(poll_interval)

    def attach_telemetry(self, telemetry) -> None:
        """Route this client's control-plane timings (barrier waits, barrier
        failures) into a :class:`..utils.telemetry.Telemetry` bus — the
        cluster-health half of the unified stream."""
        self._telemetry = telemetry

    def plane_failovers(self) -> int:
        """Failovers this client has ridden (the single-instance view of
        :meth:`CoordinationRouter.plane_failovers`) — the monotonic count
        ``param_sync`` polls to trigger its post-failover record replay."""
        return self.failover_count

    def barrier(self, name: str, timeout: float = 60.0) -> None:
        # Per-call nonce (time_ns: unique across restarts) makes the arrival
        # retry-safe: if the barrier released but the OK was lost on the
        # wire, the transport retry re-presents the same nonce and the
        # server re-answers OK instead of entering the next generation.
        nonce = time.time_ns()
        t0 = time.perf_counter()
        try:
            resp = self._request(
                f"BARRIER {name} {self.task_id} {timeout} {nonce}",
                timeout=timeout + 5.0)
        except CoordinationError:
            if self._telemetry is not None:
                self._telemetry.counter("barrier_failures").inc()
            raise
        wait_ms = (time.perf_counter() - t0) * 1000.0
        if self._telemetry is not None:
            # Barrier wait is where stragglers first hurt everyone else:
            # the fastest worker pays the slowest worker's lateness here.
            self._telemetry.counter("barriers").inc()
            self._telemetry.histogram("barrier_wait_ms").record(wait_ms)
        tracer = tracing.active()
        if tracer is not None:
            # Named barrier span on top of the transport-level
            # coord.barrier span: the exported trace shows WHICH barrier
            # the cluster converged on, and the wait is the straggler's
            # cost to this worker.
            tracer.emit_span("barrier_wait", time.time() - wait_ms / 1000.0,
                             wait_ms, barrier=name)
        if resp != "OK":
            if self._telemetry is not None:
                self._telemetry.counter("barrier_failures").inc()
            raise CoordinationError(f"barrier {name!r} failed: {resp}")

    def heartbeat(self, step: int | None = None) -> None:
        """Liveness ping; ``step`` (optional) reports training progress for
        the coordinator's straggler detection.  No internal retry (budget
        0): a stale beat is worthless — the next one supersedes it."""
        injector = faults.active()
        if injector is not None and injector.heartbeats_frozen():
            return  # injected frozen-process window: beats silently dropped
        if step is None:
            step = self._progress_step
        self._request(f"HEARTBEAT {self.task_id} {step}", retry_budget=0.0)

    def set_progress(self, step: int) -> None:
        """Record this task's latest step; the heartbeat thread carries it to
        the coordinator (no extra round trip on the training hot path)."""
        self._progress_step = int(step)

    def start_heartbeats(self, interval: float = 1.0) -> None:
        if self._heartbeat_thread is not None:
            return
        def loop():
            while not self._heartbeat_stop.wait(interval):
                try:
                    self.heartbeat()
                except CoordinationError:
                    pass  # a stale beat is worthless; the next one retries
                except Exception as e:  # noqa: BLE001 — latch, don't die mute
                    # Dying silently here turns into a mystery eviction
                    # minutes later; latch and surface on the next call.
                    self._latch_background_error("heartbeat", e)
                    return
        self._heartbeat_thread = threading.Thread(target=loop, daemon=True)
        self._heartbeat_thread.start()

    def kv_set(self, key: str, value: str) -> None:
        resp = self._request(f"KVSET {key} {value}")
        if resp != "OK":
            raise CoordinationError(f"kv_set failed: {resp}")

    def kv_get(self, key: str) -> str | None:
        resp = self._request(f"KVGET {key}")
        if resp.startswith("OK"):
            return resp[3:]
        return None

    def kv_wait(self, key: str, timeout: float = 60.0,
                poll_interval: float = 1.0) -> str:
        """Poll for a key — the chief-initializes/others-wait pattern
        (``prepare_or_wait_for_session``, reference ``distributed.py:121-125``).

        Polls with capped exponential backoff: the interval starts at
        ``min(0.05, poll_interval)`` and doubles up to ``poll_interval``
        (the cap).  A fast chief is noticed within ~50 ms while a long
        chief init (minutes of restore/compile) costs one syscall per
        ``poll_interval`` instead of a fixed-cadence idle spin.
        """
        deadline = time.monotonic() + timeout
        interval = min(0.05, poll_interval)
        while True:
            value = self.kv_get(key)
            if value is not None:
                return value
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise CoordinationError(f"timed out waiting for key {key!r}")
            time.sleep(min(interval, remaining))
            interval = min(interval * 2.0, poll_interval)

    def health(self, straggler_lag: int = 0) -> list[bool]:
        """Live set per task — feeds the R<N replica mask.

        Heartbeat-based liveness; with ``straggler_lag > 0`` a
        slow-but-heartbeating task more than that many steps behind the
        front-runner is also excluded (it rejoins once it catches up) — the
        reference SyncReplicasOptimizer's drop-the-slow semantics
        (``distributed.py:97-100``)."""
        resp = self._request(f"HEALTH {int(straggler_lag)}")
        if not resp.startswith("OK"):
            raise CoordinationError(f"health query failed: {resp}")
        return [bit == "1" for bit in resp.split()[1:]]

    def progress(self) -> list[int]:
        """Latest heartbeat-reported step per task (-1 = none reported)."""
        resp = self._request("PROGRESS")
        if not resp.startswith("OK"):
            raise CoordinationError(f"progress query failed: {resp}")
        return [int(s) for s in resp.split()[1:]]

    def heartbeat_ages(self) -> list[float]:
        """Seconds since each task's last heartbeat (-1.0 = never seen) —
        the raw straggler signal behind :meth:`health`, for telemetry."""
        resp = self._request("AGES")
        if not resp.startswith("OK"):
            raise CoordinationError(f"ages query failed: {resp}")
        return [float(s) for s in resp.split()[1:]]

    def info(self) -> dict:
        """Server INFO line as a dict (``num_tasks``, ``registered``,
        ``evictions``, ``epoch``, ``active``, plus the coordinator-HA
        fields ``role``, ``generation``, ``standbys``, ``repl_lag``,
        ``last_promotion_age_s``) — how standalone tools
        (``tools/watch_run.py``, ``tools/coord_shard.py --status``) learn
        the cluster and control-plane state without flags."""
        return self._parse_int_fields(self._request("INFO"), "info")

    @staticmethod
    def _parse_int_fields(resp: str, what: str) -> dict:
        """``OK key=value ...`` reply -> dict (INFO/SHARDINFO shape):
        values parse as int, then float, else stay strings (``role``)."""
        if not resp.startswith("OK"):
            raise CoordinationError(f"{what} query failed: {resp}")
        out: dict = {}
        for part in resp.split()[1:]:
            key, _, value = part.partition("=")
            try:
                out[key] = int(value)
            except ValueError:
                try:
                    out[key] = float(value)
                except ValueError:
                    out[key] = value
        return out

    def shard_info(self) -> dict[str, int]:
        """The server instance's shard identity (``shard``, ``nshards``)
        — how a :class:`CoordinationRouter` (or an operator probe) verifies
        it reached the instance a key hashed to.  A pre-sharding server
        answers ``shard=0 nshards=1``."""
        return self._parse_int_fields(self._request("SHARDINFO"),
                                      "shard info")

    def repl_join(self, addr: str = "-") -> dict:
        """Attach to the control shard's replication plane (the
        ``REPLJOIN`` snapshot bootstrap a warm standby performs; docs/
        fault_tolerance.md, "Coordinator HA").  Returns the snapshot —
        ``snap_seq``, ``generation``, ``lease_timeout``, the assigned
        ``standby_id``, and the checksum-verified state ``records`` — and
        registers this caller as a standby in the primary's ack table.
        ``addr`` is the advertised endpoint peers see in REPLSTREAM acks
        (``"-"`` = unadvertised: a tap, not a promotable standby).  Test
        and debug tooling drives this directly; production standbys run
        the C++ pull loop (``CoordinationServer(standby_of=...)``)."""
        resp = self._request(f"REPLJOIN {addr}")
        if not resp.startswith("OK"):
            raise CoordinationError(f"repl join failed: {resp}")
        chunks = resp.split("\x1e")
        head = chunks[0].split()
        out = {"snap_seq": int(head[1]), "generation": int(head[2]),
               "lease_timeout": float(head[3]),
               "standby_id": int(head[4]), "records": []}
        for chunk in chunks[1:]:
            checksum, _, body = chunk.partition(" ")
            if _fnv1a(body) != checksum:
                raise CoordinationError(
                    f"repl snapshot checksum mismatch on {body[:60]!r}")
            out["records"].append(body)
        return out

    def repl_stream(self, standby_id: int, from_seq: int) -> dict:
        """Pull one batch of the control shard's journal stream
        (``REPLSTREAM``): records ``[from_seq, latest_seq]`` as
        ``{"seq", "body"}`` dicts, sequence-checked and
        checksum-verified, behind ``latest_seq``/``generation`` and the
        per-standby ``acks`` table (``{id: {"acked_seq", "addr"}}``).
        Raises on ``ERR rejoin`` (the primary restarted and forgot this
        standby id — :meth:`repl_join` again) and ``ERR resync`` (fell
        off the bounded log — re-bootstrap)."""
        resp = self._request(f"REPLSTREAM {int(standby_id)} {int(from_seq)}")
        if not resp.startswith("OK"):
            raise CoordinationError(f"repl stream failed: {resp}")
        chunks = resp.split("\x1e")
        head = chunks[0].split()
        out = {"latest_seq": int(head[1]), "generation": int(head[2]),
               "acks": {}, "records": []}
        for token in head[3:]:
            if not token.startswith("acks=") or len(token) == 5:
                continue
            for entry in token[5:].split(","):
                sid, acked, addr = entry.split(":", 2)
                out["acks"][int(sid)] = {"acked_seq": int(acked),
                                         "addr": addr}
        expect = int(from_seq)
        for chunk in chunks[1:]:
            seq, checksum, body = chunk.split(" ", 2)
            if _fnv1a(body) != checksum:
                raise CoordinationError(
                    f"repl stream checksum mismatch at seq {seq}")
            if int(seq) != expect:
                raise CoordinationError(
                    f"repl stream sequence gap: got {seq}, want {expect}")
            expect += 1
            out["records"].append({"seq": int(seq), "body": body})
        return out

    def server_time(self) -> float:
        """The coordination server's epoch clock (seconds) — one sample of
        the ``TIME`` protocol command."""
        resp = self._request("TIME")
        if not resp.startswith("OK"):
            raise CoordinationError(f"time query failed: {resp}")
        return float(resp.split()[1])

    def clock_offset(self, samples: int = 5) -> tuple[float, float]:
        """NTP-style offset estimate against the coordination server.

        Each sample brackets a ``TIME`` request between two local
        ``time.time()`` reads and takes the midpoint; the sample with the
        smallest round trip wins (its midpoint error is bounded by rtt/2).
        Returns ``(offset_seconds, rtt_seconds)`` where *offset* is
        ``server_clock - local_clock`` — ADD it to local epoch stamps to
        land on the server's timeline.  Workers measure this once at
        startup and stamp it into their telemetry stream as a
        ``kind="clock_sync"`` record; ``tools/export_trace.py`` applies it
        when merging per-worker spans into one cross-worker trace, so the
        alignment error is bounded by the measured RTT."""
        best: tuple[float, float] | None = None
        for _ in range(max(int(samples), 1)):
            t0 = time.time()
            server = self.server_time()
            t1 = time.time()
            rtt = t1 - t0
            offset = server - (t0 + t1) / 2.0
            if best is None or rtt < best[1]:
                best = (offset, rtt)
        return best

    def stat_put(self, payload) -> None:
        """Publish one live-stats entry (a dict, JSON-encoded compactly, or
        a pre-encoded single-line string) into this task's bounded ring on
        the coordination server.  No retry (budget 0): stale stats are
        worthless — the next logged step supersedes them.  The training
        loop publishes per-step summaries here so ``tools/watch_run.py``
        can watch a live run without touching its files."""
        if not isinstance(payload, str):
            payload = json.dumps(payload, separators=(",", ":"))
        if "\n" in payload or "\x1e" in payload:
            raise ValueError(
                "stat payload must be a single line without the 0x1e "
                "record separator")
        # Sub-second timeout, no retry: this is called from the training
        # loop's log boundary — a black-holed coordinator must cost the
        # step milliseconds, not the default request timeout.
        resp = self._request(f"STATPUT {self.task_id} {payload}",
                             timeout=0.5, retry_budget=0.0)
        if resp != "OK":
            raise CoordinationError(f"stat_put failed: {resp}")

    def stat_dump(self, last: int = 1) -> list[dict]:
        """Newest ``last`` ring entries per task:
        ``[{task, age_s, seq, stat}]`` where ``age_s`` is the server-side
        seconds since receipt (staleness without trusting worker clocks)
        and ``stat`` is the decoded JSON payload (``{"raw": ...}`` when a
        publisher sent something that isn't JSON)."""
        resp = self._request(f"STATDUMP {int(last)}")
        if not resp.startswith("OK"):
            raise CoordinationError(f"stat_dump failed: {resp}")
        entries: list[dict] = []
        for chunk in resp.split("\x1e")[1:]:
            head = chunk.split(" ", 3)
            if len(head) < 3:
                continue
            raw = head[3] if len(head) > 3 else ""
            try:
                stat = json.loads(raw)
                if not isinstance(stat, dict):
                    stat = {"raw": stat}
            except ValueError:
                stat = {"raw": raw}
            entries.append({"task": int(head[0]), "age_s": float(head[1]),
                            "seq": int(head[2]), "stat": stat})
        return entries

    @staticmethod
    def _parse_members(resp: str, what: str) -> tuple[int, list[int]]:
        if not resp.startswith("OK"):
            raise CoordinationError(f"{what} query failed: {resp}")
        parts = resp.split()[1:]
        if not parts:
            raise CoordinationError(f"{what} reply missing epoch: {resp!r}")
        return int(parts[0]), [int(t) for t in parts[1:]]

    def members(self) -> tuple[int, list[int]]:
        """``(membership_epoch, active_task_ids)`` — the elastic replica
        set.  The epoch increments on every shrink (lease expiry, LEAVE,
        explicit evict) and grow (re-register, explicit admit), so callers
        can detect resizes without diffing the id list
        (docs/fault_tolerance.md, "Elastic membership")."""
        return self._parse_members(self._request("MEMBERS"), "members")

    def reconfigure(self, task: int | None = None,
                    active: bool = True) -> tuple[int, list[int]]:
        """Force a lease scan and return the authoritative
        ``(epoch, active_task_ids)``; with ``task`` set, additionally evict
        (``active=False``) or admit (``active=True``) that task explicitly —
        the chief-driven resize path."""
        line = ("RECONFIGURE" if task is None
                else f"RECONFIGURE {int(task)} {1 if active else 0}")
        return self._parse_members(self._request(line), "reconfigure")

    def start_health_polling(self, interval: float = 1.0,
                             num_tasks: int | None = None,
                             straggler_lag: int = 0) -> None:
        """Background health refresh so hot-path readers (the per-step replica
        mask) never pay a TCP round trip — they read the cached snapshot."""
        with self._health_lock:
            if not self._cached_health:
                self._cached_health = [True] * (num_tasks or 1)
        if self._health_thread is not None:
            return

        def loop():
            while not self._heartbeat_stop.wait(interval):
                try:
                    h = self.health(straggler_lag)
                except CoordinationError:
                    continue
                except Exception as e:  # noqa: BLE001 — latch, don't die mute
                    self._latch_background_error("health-poll", e)
                    return
                with self._health_lock:
                    self._cached_health = h
        self._health_thread = threading.Thread(target=loop, daemon=True)
        self._health_thread.start()

    def cached_health(self) -> list[bool]:
        """Latest background-polled health snapshot (optimistic before first poll)."""
        with self._health_lock:
            return list(self._cached_health)

    def chaos(self, *directive: object) -> None:
        """Drive the server-side fault injector (the ``CHAOS`` protocol
        command, csrc/coordination/coord.cc) — test/ops tooling only:
        ``chaos("drop", 3)`` drops the next 3 requests (connection closed
        with no response), ``chaos("dropfor", 2.5)`` drops everything for
        2.5 s, ``chaos("delay", 0.2, 5)`` delays the next 5 responses by
        0.2 s, ``chaos("off")`` clears.  The CHAOS command itself is never
        dropped/delayed, so the harness can always disarm."""
        line = " ".join(["CHAOS", *(str(d) for d in directive)])
        resp = self._request(line)
        if resp != "OK":
            raise CoordinationError(f"chaos directive failed: {resp}")

    def leave(self) -> None:
        """Voluntary departure: deregisters AND shrinks the elastic
        membership set immediately (epoch bump — survivors resize without
        waiting out our lease).  A client that never registered is not a
        member and must not shrink a live cluster (eval-mode/standalone
        clients share the coordinator address); a closed client no-ops."""
        if not self._handles or not self._registered:
            return
        try:
            self._request(f"LEAVE {self.task_id}", retry_budget=0.0)
            self._registered = False
        except CoordinationError:
            pass

    def close(self) -> None:
        self._heartbeat_stop.set()
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=5.0)
            self._heartbeat_thread = None
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        for handle in self._handles:
            self._lib.dtf_coord_client_destroy(handle)
        self._handles = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


#: Record-family suffixes that must co-locate with their base key on ONE
#: instance of a sharded coordination plane: the chunked-KV transport's
#: commit-point ordering (chunks, then ``.fp``, then the meta entry) and
#: the version hints (``.v``/``.hint``/``.tfp``) are only meaningful
#: against the same instance's view of the base entry.
_FAMILY_SUFFIXES = (".fp", ".v", ".hint", ".tfp")


def router_base_key(key: str) -> str:
    """The routing key of a KV entry: its record family's base key.

    ``<base>.c<i>`` chunk entries and the ``.fp``/``.v``/``.hint``/``.tfp``
    side entries all hash as ``<base>``, so one publication's whole key
    family lands on one instance — write ordering (chunks before the meta
    commit point) and torn-read detection keep their single-instance
    semantics under the sharded plane."""
    for suffix in _FAMILY_SUFFIXES:
        if key.endswith(suffix):
            return key[:-len(suffix)]
    dot = key.rfind(".c")
    if dot > 0 and key[dot + 2:].isdigit():
        return key[:dot]
    return key


class CoordinationRouter:
    """Client facade over a sharded coordination plane (docs/
    param_exchange.md, "Hierarchical exchange").

    The KV/blob plane spreads across ``M`` coordinator instances by stable
    key hash (``crc32(router_base_key(key)) % M``); membership, barriers,
    leases, heartbeats, stats, and every other control command stay pinned
    to instance 0 — the **control shard** — so there is exactly one
    authoritative membership epoch.  Each instance's requests retry/fail
    over independently with the owning client's existing jittered-backoff
    budget: one dead KV shard makes *its* keys unavailable (callers see
    the usual :class:`CoordinationTransportError` and degrade as they
    already do for a flat coordinator) without touching the control plane
    or the other shards.

    The facade duck-types :class:`CoordinationClient` (same method
    surface), so averagers, supervisors, and watchers take either.

    ``standbys`` (optional) wires per-instance ordered warm-standby lists
    — any :func:`parse_standby_map` form (docs/fault_tolerance.md,
    "KV-shard HA").  Each instance's client walks ITS list on a dead or
    demoted primary exactly like the control-shard client (PR 15's
    endpoint walk generalized to every shard); KV shards stamp the
    recovery record ``action="kv_shard_failover"`` with their shard id.
    ``control_standbys`` (``"host:port,..."``) is the pre-sharded-HA
    alias: standbys of the CONTROL shard (instance 0) only."""

    def __init__(self, addresses, task_id: int,
                 incarnation: int | None = None,
                 control_standbys=None, standbys=None, **client_kwargs):
        parsed = _parse_endpoints(addresses)
        if not parsed:
            raise ValueError("coordination router needs >= 1 instance")
        standby_map = parse_standby_map(standbys)
        if control_standbys:
            standby_map.setdefault(0, control_standbys)
        for idx in standby_map:
            if not 0 <= idx < len(parsed):
                raise ValueError(
                    f"standby map names instance {idx} but the plane has "
                    f"{len(parsed)} instance(s)")
        self._clients = []
        for i, (host, port) in enumerate(parsed):
            kwargs = dict(client_kwargs)
            if standby_map.get(i):
                kwargs["standbys"] = standby_map[i]
            if i > 0:
                # KV data shard: failovers are per-shard recovery events.
                kwargs["failover_shard"] = i
            self._clients.append(
                CoordinationClient(host, port, task_id,
                                   incarnation=incarnation, **kwargs))
        self.addresses = parsed

    @classmethod
    def observer(cls, addresses, retry_budget: float = 2.0,
                 standbys=None) -> "CoordinationRouter":
        """Observer router (task_id -1, never registers) — the sharded
        counterpart of :meth:`CoordinationClient.observer`."""
        return cls(addresses, task_id=-1, retry_budget=retry_budget,
                   standbys=standbys)

    @property
    def control(self) -> CoordinationClient:
        """Instance 0 — the control shard every non-KV command goes to."""
        return self._clients[0]

    @property
    def num_instances(self) -> int:
        return len(self._clients)

    def instance_for(self, key: str) -> int:
        return zlib.crc32(router_base_key(key).encode()) \
            % len(self._clients)

    def instance_client(self, index: int) -> CoordinationClient:
        return self._clients[index]

    def _kv_client(self, key: str) -> CoordinationClient:
        return self._clients[self.instance_for(key)]

    # -- routed KV/blob traffic ------------------------------------------

    def kv_set(self, key: str, value: str) -> None:
        self._kv_client(key).kv_set(key, value)

    def kv_get(self, key: str) -> str | None:
        return self._kv_client(key).kv_get(key)

    def kv_wait(self, key: str, timeout: float = 60.0,
                poll_interval: float = 1.0) -> str:
        return self._kv_client(key).kv_wait(key, timeout=timeout,
                                            poll_interval=poll_interval)

    # -- whole-plane plumbing --------------------------------------------

    def attach_telemetry(self, telemetry) -> None:
        for client in self._clients:
            client.attach_telemetry(telemetry)

    def check_background(self) -> None:
        for client in self._clients:
            client.check_background()

    def shard_map(self) -> list[dict[str, int]]:
        """Every instance's SHARDINFO identity, in route order — the
        bring-up/debug probe that catches a mis-wired instance list."""
        return [c.shard_info() for c in self._clients]

    def plane_failovers(self) -> int:
        """Total failovers ridden across every instance's client — a
        monotonic counter.  A bump means some primary died and a standby
        was promoted, so writes the dead primary acknowledged inside its
        replication-lag window may be gone; ``param_sync`` polls this
        each period and replays its write-once records when it moves."""
        return sum(c.failover_count for c in self._clients)

    def leave(self) -> None:
        self.control.leave()

    def close(self) -> None:
        for client in self._clients:
            client.close()

    def __getattr__(self, name):
        # Everything else (register, barrier, heartbeat, members, stats,
        # time, health polling, task_id/_progress_step, ...) is
        # control-shard state: delegate to instance 0, the one place
        # membership lives.  The router's own attributes are exempt so a
        # half-built self can never recurse here.
        if name in ("_clients", "addresses"):
            raise AttributeError(name)
        return getattr(self._clients[0], name)

    def __enter__(self) -> "CoordinationRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class MembershipWatcher:
    """Background view of the coordination service's elastic membership.

    Polls ``MEMBERS`` every ``interval`` seconds and caches the latest
    ``(epoch, active_task_ids)`` snapshot for hot-path readers (the R<N
    replica mask reads it exactly like the cached health bits — no TCP on
    the step path).  Every epoch change is recorded as a transition event
    and, with telemetry attached, emitted as a ``kind="recovery"`` record:
    ``action="elastic_shrink"`` when the active set got smaller,
    ``action="elastic_grow"`` when it got larger (``elastic_reshape`` for
    an equal-size swap), carrying the epoch and the active count — the
    resize trail ``tools/summarize_run.py`` rolls into the run report.

    Poll failures keep the last snapshot (an unreachable coordinator is a
    health problem, not a membership decision); the watcher itself must
    never take training down.
    """

    def __init__(self, client: CoordinationClient, num_tasks: int,
                 interval: float = 1.0, telemetry=None,
                 print_fn=print):
        if num_tasks < 1:
            raise ValueError(f"num_tasks must be >= 1, got {num_tasks}")
        self._client = client
        self._num_tasks = num_tasks
        self._interval = interval
        self._telemetry = telemetry
        self._print = print_fn
        self._step_fn = lambda: 0
        self._lock = threading.Lock()
        # Optimistic before the first successful poll (epoch 0 = no server
        # data yet): everyone is presumed active, matching the server's own
        # bring-up semantics.
        self._epoch = 0
        self._active: tuple[int, ...] = tuple(range(num_tasks))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: transition log: dicts with action/epoch/active (test surface)
        self.events: list[dict] = []

    def attach_telemetry(self, telemetry) -> None:
        self._telemetry = telemetry

    def set_step_fn(self, fn) -> None:
        """Current-step callable used to key the recovery records."""
        self._step_fn = fn

    # -- reads (lock-guarded snapshot; safe from the step hot path) -------

    def snapshot(self) -> tuple[int, tuple[int, ...]]:
        with self._lock:
            return self._epoch, self._active

    @property
    def epoch(self) -> int:
        return self.snapshot()[0]

    def active_tasks(self) -> tuple[int, ...]:
        return self.snapshot()[1]

    def is_active(self, task: int) -> bool:
        return task in self.snapshot()[1]

    def active_mask(self, num_tasks: int | None = None) -> list[bool]:
        """Per-task membership bits, same shape as
        :meth:`CoordinationClient.health` — AND the two for the replica
        mask (membership says who belongs, health says who is answering)."""
        n = self._num_tasks if num_tasks is None else num_tasks
        active = set(self.snapshot()[1])
        return [t in active for t in range(n)]

    # -- refresh ----------------------------------------------------------

    def poll(self) -> tuple[int, tuple[int, ...]]:
        """One synchronous refresh (also the test hook); returns the
        snapshot, last-known on coordinator failure.  A latched
        background-thread crash (CoordinationBackgroundError) propagates —
        swallowing it here would hide a dead heartbeat thread behind a
        forever-stale snapshot."""
        try:
            epoch, active = self._client.members()
        except CoordinationBackgroundError:
            raise
        except CoordinationError:
            if self._telemetry is not None:
                self._telemetry.counter("membership_poll_failures").inc()
            return self.snapshot()
        self._apply(epoch, tuple(active))
        return self.snapshot()

    def wait_for_epoch(self, min_epoch: int, timeout: float = 30.0,
                       poll_interval: float = 0.1) -> tuple[int, tuple[int, ...]]:
        """Poll until the epoch reaches ``min_epoch`` (resize rendezvous
        for tests and the rejoin path); raises CoordinationError on
        timeout."""
        deadline = time.monotonic() + timeout
        while True:
            epoch, active = self.poll()
            if epoch >= min_epoch:
                return epoch, active
            if time.monotonic() >= deadline:
                raise CoordinationError(
                    f"membership epoch never reached {min_epoch} "
                    f"(last seen {epoch})")
            time.sleep(poll_interval)

    def _apply(self, epoch: int, active: tuple[int, ...]) -> None:
        with self._lock:
            prev_epoch, prev_active = self._epoch, self._active
            if epoch == prev_epoch:
                return
            self._epoch, self._active = epoch, active
        if prev_epoch == 0:
            # First server contact: adopting the authoritative view is not
            # a resize unless the set actually differs from presumed-full.
            if len(active) == self._num_tasks:
                return
        if len(active) < len(prev_active):
            action = "elastic_shrink"
        elif len(active) > len(prev_active):
            action = "elastic_grow"
        else:
            action = "elastic_reshape"
        event = dict(action=action, epoch=epoch,
                     active_count=len(active), active=list(active))
        self.events.append(event)
        self._print(f"MembershipWatcher: {action} to epoch {epoch} "
                    f"(active {list(active)})")
        if self._telemetry is not None:
            try:
                step = int(self._step_fn())
            except Exception:
                step = 0
            self._telemetry.counter(action).inc()
            self._telemetry.emit("recovery", step=max(step, 0), **event)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self._interval):
                try:
                    self.poll()
                except CoordinationBackgroundError:
                    # The owning client's heartbeat/health thread died; the
                    # latch will surface on the next main-thread protocol
                    # call (the elastic controller checks every step) —
                    # stop polling rather than spin on the same error.
                    return
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="membership-watcher")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MembershipWatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ClusterHealthReporter:
    """Periodic cluster-health snapshots into the telemetry stream.

    Every ``interval`` seconds a background thread queries the coordination
    service for the per-task liveness bits, heartbeat ages, and progress
    steps, derives the straggler gap (front-runner step minus slowest live
    task's step), and emits one ``kind="cluster_health"`` record through
    the :class:`..utils.telemetry.Telemetry` bus.  Stragglers and dead
    workers thus show up in the same per-host JSONL stream as the step
    timings — visible in ``tools/summarize_run.py`` — instead of only as
    eventual barrier timeouts.

    A query failure emits a ``coordinator_reachable: false`` record rather
    than raising: the reporter must never be able to take training down,
    and an unreachable coordinator is itself a health signal worth a line
    in the stream.
    """

    def __init__(self, client: CoordinationClient, telemetry,
                 num_tasks: int, interval: float = 10.0,
                 straggler_lag: int = 0):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self._client = client
        self._telemetry = telemetry
        self._num_tasks = num_tasks
        self._interval = interval
        self._straggler_lag = straggler_lag
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._step_fn = lambda: 0  # current global step for record keying
        self._prev_alive: list[bool] | None = None
        self._evicted: set[int] = set()  # tasks seen alive, then dead
        self.snapshots = 0

    def set_step_fn(self, fn) -> None:
        """Provide the 'current step' callable used to key records (e.g.
        the rate meter's total); defaults to 0."""
        self._step_fn = fn

    def tick(self) -> dict | None:
        """One snapshot: query, derive, emit.  Returns the emitted fields
        (None when the coordinator was unreachable) — also the test hook."""
        try:
            alive = self._client.health(self._straggler_lag)
            ages = self._client.heartbeat_ages()
            progress = self._client.progress()
        except CoordinationError:
            self._telemetry.counter("health_poll_failures").inc()
            self._telemetry.emit("cluster_health", step=self._safe_step(),
                                 coordinator_reachable=False)
            return None
        n = self._num_tasks
        alive, ages, progress = alive[:n], ages[:n], progress[:n]
        # Liveness *transitions* are recovery events in their own right:
        # a peer leaving the live set (heartbeat death or straggler
        # exclusion — an eviction) and an EVICTED peer coming back (a
        # rejoin) each get one kind-tagged record, so summarize_run can
        # name what happened instead of leaving it implicit in adjacent
        # snapshots.  Rejoin is gated on a prior eviction: a worker merely
        # registering late during normal bring-up (dead->alive with no
        # alive history) is not a recovery and must not pollute the
        # recovery stream chaos assertions key on.
        if self._prev_alive is not None and len(self._prev_alive) == len(alive):
            for task, (was, now) in enumerate(zip(self._prev_alive, alive)):
                if was and not now:
                    self._evicted.add(task)
                    self._telemetry.counter("peer_evictions").inc()
                    self._telemetry.emit(
                        "recovery", step=self._safe_step(),
                        action="peer_eviction", task=task)
                elif now and not was and task in self._evicted:
                    self._evicted.discard(task)
                    self._telemetry.counter("peer_rejoins").inc()
                    self._telemetry.emit(
                        "recovery", step=self._safe_step(),
                        action="peer_rejoin", task=task)
        self._prev_alive = list(alive)
        live_steps = [s for ok, s in zip(alive, progress) if ok and s >= 0]
        straggler_gap = (max(live_steps) - min(live_steps)
                         if len(live_steps) >= 2 else 0)
        max_age = max((a for a in ages if a >= 0), default=-1.0)
        fields = dict(
            coordinator_reachable=True,
            alive=[int(b) for b in alive],
            alive_count=sum(alive),
            dead_count=n - sum(alive),
            # Structured eviction state (tasks seen alive, then dead, and
            # not yet back): consumers get the evicted peer LIST, not just
            # the free-text INFO line the transitions used to leave behind.
            evicted=sorted(self._evicted),
            heartbeat_age_s=[round(a, 3) for a in ages],
            max_heartbeat_age_s=round(max_age, 3),
            progress=progress,
            straggler_gap_steps=straggler_gap,
        )
        self._telemetry.gauge("cluster_alive").set(sum(alive))
        self._telemetry.gauge("cluster_straggler_gap").set(straggler_gap)
        self._telemetry.histogram("heartbeat_age_s").record(max(max_age, 0.0))
        self._telemetry.emit("cluster_health", step=self._safe_step(),
                             **fields)
        self.snapshots += 1
        return fields

    def _safe_step(self) -> int:
        try:
            return int(self._step_fn())
        except Exception:
            return 0

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self._interval):
                self.tick()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "ClusterHealthReporter":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()
