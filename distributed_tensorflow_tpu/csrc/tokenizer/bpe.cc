// Byte-pair-encoding tokenizer core — the hot loops of the LM data pipeline.
//
// The reference's data layer is the TF input_data reader (reference
// ``distributed.py:6,38``) backed by native TF kernels; this framework's LM
// corpus path (data/lm.py) likewise keeps its hot loops native: BPE training
// (pair counting + merge compaction over the whole corpus) and corpus
// encoding run here, reached from Python over a C ABI via ctypes
// (data/tokenizer.py), mirroring src/coordination/coord.cc's build pattern.
//
// Token model: byte-level BPE. Base vocabulary is the 256 byte values; merge
// rank r creates token id 256+r from the adjacent pair (left, right). Both
// training and encoding apply merges greedily left-to-right, rank by rank —
// deterministic for a fixed corpus, ties broken toward the numerically
// smallest (left, right) pair.

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

// Non-overlapping left-to-right replacement of (a, b) -> id, in place.
// Returns the new length.
int64_t merge_pass(std::vector<int32_t>& seq, int64_t n, int32_t a, int32_t b,
                   int32_t id) {
  int64_t w = 0, i = 0;
  while (i < n) {
    if (i + 1 < n && seq[i] == a && seq[i + 1] == b) {
      seq[w++] = id;
      i += 2;
    } else {
      seq[w++] = seq[i++];
    }
  }
  return w;
}

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

}  // namespace

extern "C" {

// Train BPE on a byte corpus.  Writes up to max_merges (left, right) pairs
// into merges_out (layout [max_merges][2]) and returns the number actually
// produced.  Training stops early when the best remaining pair occurs fewer
// than min_pair_count times (pass 2 to stop at singleton pairs).
int dtf_bpe_train(const uint8_t* data, int64_t n, int max_merges,
                  int min_pair_count, int32_t* merges_out) {
  std::vector<int32_t> seq(n);
  for (int64_t i = 0; i < n; ++i) seq[i] = data[i];
  int64_t len = n;
  if (min_pair_count < 2) min_pair_count = 2;

  std::unordered_map<uint64_t, int64_t> counts;
  counts.reserve(1 << 16);
  int produced = 0;
  for (; produced < max_merges; ++produced) {
    counts.clear();
    for (int64_t i = 0; i + 1 < len; ++i) {
      ++counts[pair_key(seq[i], seq[i + 1])];
    }
    int64_t best_count = 0;
    uint64_t best_key = 0;
    for (const auto& kv : counts) {
      if (kv.second > best_count ||
          (kv.second == best_count && kv.first < best_key)) {
        best_count = kv.second;
        best_key = kv.first;
      }
    }
    if (best_count < min_pair_count) break;
    const int32_t a = static_cast<int32_t>(best_key >> 32);
    const int32_t b = static_cast<int32_t>(best_key & 0xffffffffu);
    merges_out[2 * produced] = a;
    merges_out[2 * produced + 1] = b;
    len = merge_pass(seq, len, a, b, 256 + produced);
  }
  return produced;
}

// Encode a byte corpus with a trained merge table (rank order).  out must
// have capacity for n ids; returns the encoded length (<= n).
int64_t dtf_bpe_encode(const uint8_t* data, int64_t n, const int32_t* merges,
                       int n_merges, int32_t* out) {
  std::vector<int32_t> seq(n);
  for (int64_t i = 0; i < n; ++i) seq[i] = data[i];
  int64_t len = n;
  for (int r = 0; r < n_merges && len > 1; ++r) {
    len = merge_pass(seq, len, merges[2 * r], merges[2 * r + 1], 256 + r);
  }
  std::memcpy(out, seq.data(), len * sizeof(int32_t));
  return len;
}

}  // extern "C"
