// dtf-tpu coordination service — C++ control plane (N1 replacement).
//
// The reference's distributed runtime is TensorFlow's C++ gRPC server
// (reference distributed.py:54: tf.train.Server starts MasterService +
// WorkerService).  On TPU the data plane (parameter pull / gradient push)
// is gone — XLA collectives over ICI carry tensors — so the native runtime
// that remains is a control plane over DCN:
//
//   - task registration with incarnation numbers (restart detection)
//   - named barriers across all live tasks (sync-mode step gating / init)
//   - heartbeat-based health tracking with optional step progress
//     (straggler & failure detection: a slow-but-alive task that falls more
//     than a caller-chosen lag behind the front-runner is excluded from the
//     live set — the reference SyncReplicasOptimizer's R-of-N
//     stale-gradient-drop semantics, distributed.py:92-100 — and rejoins
//     automatically once it catches up; feeds the R<N replica mask of
//     parallel/sync.py)
//   - a key-value store (variable-initialized flags, checkpoint locations,
//     async-published parameters, chief election state — what the
//     reference's Supervisor asked its master for, distributed.py:125),
//     optionally journaled to disk so a restarted coordination service
//     restores it (the durability role the reference's PS held implicitly)
//   - elastic membership: a monotonically increasing *membership epoch*
//     over the active task set.  Every task starts presumed-active (so
//     bring-up still gates on num_tasks); a lease expiry or an explicit
//     LEAVE shrinks the set and bumps the epoch, a re-REGISTER grows it
//     and bumps again.  Barriers release on the ACTIVE set, not on
//     num_tasks, so survivors stop stalling behind the dead — the
//     reference's async PS mode degraded this gracefully by construction
//     (surviving workers kept pushing gradients, distributed.py:102);
//     here the same property holds for the sync path via the R<N mask.
//     MEMBERS reads (epoch, active ids); RECONFIGURE forces a lease scan
//     (and can explicitly evict/admit a task — chief-driven resizes).
//   - observability plumbing: TIME exposes the server's epoch clock so
//     workers can estimate their clock offset (NTP-style midpoint) and
//     the exported cross-worker trace aligns; STATPUT/STATDUMP keep a
//     bounded per-task ring of opaque live-stats lines so a watcher
//     (tools/watch_run.py) can see a running cluster without touching
//     its files (docs/observability.md).
//   - coordinator HA (docs/fault_tolerance.md, "Coordinator HA"): a
//     control shard runs as *primary* or *standby*.  The primary appends
//     every state transition (KV sets, membership epochs, barrier
//     releases and their per-call nonces, registration, leadership-lease
//     renewals) to an in-memory replication log; standbys pull it over
//     the REPLJOIN (snapshot bootstrap) / REPLSTREAM (sequence-numbered,
//     checksummed batches) command pair and apply the records into the
//     same in-memory state machine.  A standby refuses mutating commands
//     with "NOTPRIMARY <leader>", and on losing contact with the primary
//     past the leadership lease the most-caught-up standby promotes
//     itself: coordinator *generation* bumps (persisted, and echoed in
//     every reply's 0x1f trailer so clients fence stale primaries),
//     barriers re-arm conservatively (replicated nonces re-answer
//     in-flight calls, never double-release), and every registered task
//     is presumed active until the first heartbeat round re-establishes
//     leases — the same presumed-active rule bring-up uses.
//
// Wire protocol: one TCP connection per request, single request line,
// single "OK ..." / "ERR ..." / "NONE" response line, plus a 0x1f-
// separated "gen=<g> role=<r>" trailer on every reply (the stale-primary
// fence).  Python binds via ctypes to the C ABI at the bottom (no
// pybind11 in the image).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace dtf {

using Clock = std::chrono::steady_clock;

static double NowSeconds() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch()).count();
}

// Checksum for the replication wire format (FNV-1a 32-bit, hex): cheap,
// dependency-free, and mirrored by the Python client's verifier.  It
// guards against torn/corrupted records on the stream, not adversaries.
static std::string Fnv1a(const std::string& s) {
  unsigned long h = 2166136261ul;
  for (unsigned char c : s) {
    h ^= c;
    h = (h * 16777619ul) & 0xFFFFFFFFul;
  }
  char buf[9];
  std::snprintf(buf, sizeof(buf), "%08lx", h);
  return std::string(buf);
}

// One replicated state transition (the journal-streamed record a standby
// applies).  Body grammar (single line, space-separated head):
//   K <key> <value>            KV set
//   R <task> <inc> <restarts> <registered>   registration transition
//   M <epoch> <id,id,...|->    membership epoch + active set
//   B <name> <generation>      barrier release (generation bump)
//   N <name> <task> <nonce>    per-call done-nonce (retry idempotency)
//   L 1                        leadership-lease renewal (liveness marker)
//   G <generation>             coordinator-generation bump (promotion)
struct ReplRecord {
  long seq = 0;
  std::string body;
};

// 0x1e frames replication/STATDUMP records and 0x1f the reply trailer:
// any CLIENT-supplied string that reaches a replicated record or a reply
// (KV keys and values, barrier names, stat payloads, advertised standby
// addresses) must exclude both, or one hostile/buggy caller corrupts
// every standby's stream and every reader's trailer parse — not just its
// own entry.
static bool HasReservedByte(const std::string& s) {
  return s.find('\x1e') != std::string::npos ||
         s.find('\x1f') != std::string::npos;
}

struct TaskInfo {
  long incarnation = 0;
  double last_heartbeat = 0.0;
  long last_step = -1;  // progress carried in heartbeats; -1 = never reported
  int restarts = 0;
  bool registered = false;
  bool evicted = false;  // lease expired (heartbeat silence) since last seen
};

// One live-stats ring entry (the STATPUT/STATDUMP protocol pair): an
// opaque payload line a worker published (compact JSON from the training
// loop), stamped with the server's receipt time so readers see staleness
// without trusting worker clocks.
struct StatEntry {
  double recv_time = 0.0;  // server steady-clock receipt time
  long seq = 0;            // server-global publish sequence number
  std::string payload;
};

struct BarrierState {
  std::set<int> arrived;
  long generation = 0;  // bumped when a barrier releases, so reuse works
  // Nonce each arrival presented, captured at arrival time so the
  // RELEASE path can mark every arrived call done in one place (and
  // stream the transitions to standbys) instead of each waiter marking
  // itself as it wakes — a primary dying mid-release then leaves no
  // waiter un-re-answerable on the promoted standby.
  std::map<int, long> arrival_nonce;
  // Last successfully-released call nonce per task: a transport-level
  // RETRY of an arrival whose barrier already released (response lost on
  // the wire) must return OK instead of entering the next generation.
  std::map<int, long> done_nonce;
};

// --- Client: connection-per-request (poll semantics match the reference's
// recovery_wait_secs=1 poll loop, distributed.py:111,125).  Defined ahead
// of the server because a standby's replication pull loop IS a client of
// its primary. ---

class CoordClient {
 public:
  CoordClient(std::string host, int port, int task_id)
      : host_(std::move(host)), port_(port), task_id_(task_id) {}

  int task_id() const { return task_id_; }

  bool Request(const std::string& line, std::string* response,
               double timeout_sec) {
    int fd = Connect(timeout_sec);
    if (fd < 0) return false;
    std::string msg = line + "\n";
    size_t off = 0;
    while (off < msg.size()) {
      ssize_t n = ::send(fd, msg.data() + off, msg.size() - off, MSG_NOSIGNAL);
      if (n <= 0) {
        ::close(fd);
        return false;
      }
      off += static_cast<size_t>(n);
    }
    response->clear();
    // Buffered response read (one response line per connection): the
    // byte-at-a-time version made large KVGET responses pay a syscall per
    // byte and time out at chunk scale.
    char buf[65536];
    bool done = false;
    while (!done) {
      ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
      if (n <= 0) break;
      for (ssize_t i = 0; i < n; ++i) {
        if (buf[i] == '\n') {
          done = true;
          break;
        }
        response->push_back(buf[i]);
      }
    }
    ::close(fd);
    return !response->empty();
  }

 private:
  int Connect(double timeout_sec) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_str = std::to_string(port_);
    if (::getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res) != 0)
      return -1;
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0) {
      timeval tv;
      tv.tv_sec = static_cast<long>(timeout_sec);
      tv.tv_usec = static_cast<long>((timeout_sec - tv.tv_sec) * 1e6);
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      if (::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
        ::close(fd);
        fd = -1;
      }
    }
    ::freeaddrinfo(res);
    return fd;
  }

  std::string host_;
  int port_;
  int task_id_;
};

class CoordServer {
 public:
  CoordServer(int port, int num_tasks, double heartbeat_timeout,
              const std::string& persist_path = "", int shard = 0,
              int nshards = 1, const std::string& primary_addr = "",
              double lease_timeout = 2.0,
              const std::string& advertise_addr = "")
      : num_tasks_(num_tasks), heartbeat_timeout_(heartbeat_timeout),
        persist_path_(persist_path), shard_(shard),
        nshards_(nshards < 1 ? 1 : nshards), primary_addr_(primary_addr),
        lease_timeout_(lease_timeout > 0 ? lease_timeout : 2.0),
        advertise_addr_(advertise_addr) {
    // Shard identity is fixed BEFORE the accept thread below spawns, so
    // no client — not even one racing bring-up on a fixed port — can
    // ever read the default identity from a sharded instance.  Role and
    // generation likewise: a standby must never answer its first request
    // as a primary, and a restarted instance must come back with its
    // persisted generation (the split-brain fence), not generation 1.
    if (!persist_path_.empty()) LoadJournal();
    LoadMeta();
    is_primary_.store(primary_addr_.empty());
    gen_atomic_.store(generation_);
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        ::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    running_.store(true);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    if (!primary_addr_.empty()) {
      // Standby: the replication pull loop starts immediately (snapshot
      // bootstrap, then sequential stream).  A primary starts its lease
      // ticker lazily, on the first REPLJOIN.
      std::lock_guard<std::mutex> lock(mu_);
      StartReplThreadLocked();
    }
  }

  ~CoordServer() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  // Shard identity of a sharded coordination plane (SHARDINFO).  Prefer
  // the constructor parameters (identity fixed before the accept thread
  // exists); this setter remains for callers holding an already-running
  // server.
  void SetShard(int shard, int nshards) {
    std::lock_guard<std::mutex> lock(mu_);
    shard_ = shard;
    nshards_ = nshards < 1 ? 1 : nshards;
  }

  void Stop() {
    bool expected = true;
    if (!running_.compare_exchange_strong(expected, false)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> lock(mu_);
      shutting_down_ = true;
    }
    barrier_cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    // Wait for detached handler threads (barrier waiters are woken above).
    {
      std::unique_lock<std::mutex> lock(workers_mu_);
      workers_done_cv_.wait(lock, [this] { return active_handlers_ == 0; });
    }
    // The replication thread applies records into the journal, so it must
    // be gone before the journal handle closes below.
    if (repl_thread_.joinable()) repl_thread_.join();
    std::lock_guard<std::mutex> lock(mu_);
    if (journal_ != nullptr) {
      std::fclose(journal_);
      journal_ = nullptr;
    }
  }

  void Join() {
    if (accept_thread_.joinable()) accept_thread_.join();
  }

 private:
  void AcceptLoop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      {
        std::lock_guard<std::mutex> lock(workers_mu_);
        ++active_handlers_;
      }
      std::thread([this, fd] {
        Handle(fd);
        std::lock_guard<std::mutex> lock(workers_mu_);
        if (--active_handlers_ == 0) workers_done_cv_.notify_all();
      }).detach();
    }
  }

  static bool ReadLine(int fd, std::string* out) {
    // Buffered reads: the protocol is one request line per connection, so
    // bulk recv() is safe (no bytes follow the newline) and necessary —
    // byte-at-a-time recv costs a syscall per byte, which pushed
    // chunk-scale KV values (512 KiB parameter chunks from param_sync.py)
    // past the client's request timeout.
    out->clear();
    char buf[65536];
    while (true) {
      ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
      if (n <= 0) return false;
      for (ssize_t i = 0; i < n; ++i) {
        if (buf[i] == '\n') return true;
        out->push_back(buf[i]);
      }
      // Request-line cap: KV values (async-published parameters arrive as
      // chunked entries from param_sync.py) stay well under this; the cap
      // only bounds a runaway/hostile client.
      if (out->size() > (8u << 20)) return false;
    }
  }

  static void WriteLine(int fd, const std::string& line) {
    std::string msg = line + "\n";
    size_t off = 0;
    while (off < msg.size()) {
      ssize_t n = ::send(fd, msg.data() + off, msg.size() - off, MSG_NOSIGNAL);
      if (n <= 0) return;
      off += static_cast<size_t>(n);
    }
  }

  // Every reply carries a 0x1f-separated generation/role trailer: the
  // stale-primary fence.  A client that has seen generation G treats any
  // reply stamped < G as coming from a dead generation's ghost and walks
  // its endpoint list instead of accepting the answer.  Reads atomics
  // only — callers hold mu_ at some call sites and not at others.
  void Reply(int fd, const std::string& line) {
    std::ostringstream os;
    os << line << '\x1f' << "gen=" << gen_atomic_.load() << " role="
       << (is_primary_.load() ? "primary" : "standby");
    WriteLine(fd, os.str());
  }

  void Handle(int fd) {
    // Bound the initial read so a client that connects and dies without
    // sending a request line can't pin this handler (and hang Stop()) forever.
    timeval tv{};
    tv.tv_sec = 30;
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    std::string line;
    if (ReadLine(fd, &line)) {
      std::istringstream iss(line);
      std::string cmd;
      iss >> cmd;
      // Optional generation guard: clients prefix requests with
      // "gen=<highest generation seen>" (lowercase: not a command).
      // A server BEHIND that generation is a stale ghost — a restarted
      // pre-promotion primary — and must refuse WITHOUT executing, or a
      // fenced reply would still leave a split-brain write applied.
      long client_gen = -1;
      if (cmd.rfind("gen=", 0) == 0) {
        client_gen = std::atol(cmd.c_str() + 4);
        cmd.clear();
        iss >> cmd;
      }
      // Fault injection (the CHAOS command below arms it): drop = close the
      // connection without a response (the client sees a transport failure
      // and exercises its retry/backoff path), delay = respond late.  CHAOS
      // itself is exempt so the harness can always disarm; the replication
      // pair is exempt too — CHAOS models the client-facing network, and a
      // drop window must not masquerade as a dead leader and trigger a
      // promotion mid-test.
      if (cmd != "CHAOS" && cmd != "REPLJOIN" && cmd != "REPLSTREAM") {
        bool drop = false;
        double delay = 0.0;
        {
          std::lock_guard<std::mutex> lock(mu_);
          if (chaos_drop_ > 0) {
            chaos_drop_--;
            drop = true;
          } else if (chaos_drop_until_ > NowSeconds()) {
            drop = true;
          } else if (chaos_delay_ > 0 && chaos_delay_secs_ > 0) {
            chaos_delay_--;
            delay = chaos_delay_secs_;
          }
        }
        if (drop) {
          ::close(fd);
          return;
        }
        if (delay > 0)
          std::this_thread::sleep_for(
              std::chrono::duration<double>(delay));
      }
      // Refusal gates.  (1) Generation fence: the caller has seen a
      // NEWER coordinator generation than this server holds — this
      // server is a dead generation's ghost and must not execute the
      // request (the no-split-brain-writes rule).  (2) Standby refusal:
      // a warm standby applies the primary's stream but serves no state
      // of its own — a mutating command accepted here would fork the
      // state machine, and even reads could hand out a stale membership
      // view.  In both cases identity/clock probes (INFO, SHARDINFO,
      // TIME) and the chaos harness stay answerable so an operator can
      // probe role, generation, and replication lag; everything else
      // redirects to the leader ("-" when this server cannot name one).
      bool diagnostic = cmd == "INFO" || cmd == "SHARDINFO" ||
                        cmd == "TIME" || cmd == "CHAOS";
      bool fenced = client_gen > gen_atomic_.load();
      if ((fenced || !is_primary_.load()) && !diagnostic) {
        std::string leader;
        {
          std::lock_guard<std::mutex> lock(mu_);
          leader = primary_addr_.empty() ? "-" : primary_addr_;
        }
        Reply(fd, "NOTPRIMARY " + leader);
        ::close(fd);
        return;
      }
      if (cmd == "REGISTER") {
        int task;
        long inc;
        iss >> task >> inc;
        Reply(fd, Register(task, inc));
      } else if (cmd == "HEARTBEAT") {
        int task;
        long step = -1;
        iss >> task;
        // Step is optional (liveness-only heartbeat); a failed extraction
        // writes 0 since C++11, so restore the "no report" sentinel.
        if (!(iss >> step)) step = -1;
        Heartbeat(task, step);
        Reply(fd, "OK");
      } else if (cmd == "BARRIER") {
        std::string name;
        int task;
        double timeout;
        long nonce = 0;  // optional per-call id (retry idempotency)
        iss >> name >> task >> timeout;
        if (!(iss >> nonce)) nonce = 0;
        if (HasReservedByte(name)) {
          // Barrier names land in replicated "B <name>"/"N <name>"
          // records — same framing-corruption blast radius as KV below.
          Reply(fd, "ERR barrier name contains a reserved framing byte");
        } else {
          Reply(fd, Barrier(name, task, timeout, nonce));
        }
      } else if (cmd == "KVSET") {
        std::string key, value;
        iss >> key;
        std::getline(iss, value);
        if (!value.empty() && value[0] == ' ') value.erase(0, 1);
        if (HasReservedByte(key) || HasReservedByte(value)) {
          // Key AND value both reach the replicated record and the
          // KVGET reply: either carrying a framing byte would corrupt
          // every standby's view (or every client's trailer parse), not
          // just this caller's entry.  KV publishers (param_sync) are
          // base64/ASCII by construction, so this only bounds a hostile
          // client.
          Reply(fd, "ERR kvset key/value contains a reserved framing "
                    "byte");
        } else {
          {
            std::lock_guard<std::mutex> lock(mu_);
            kv_[key] = value;
            AppendJournal(key, value);
            AppendReplLocked("K " + key + " " + value);
          }
          Reply(fd, "OK");
        }
      } else if (cmd == "KVGET") {
        std::string key;
        iss >> key;
        std::lock_guard<std::mutex> lock(mu_);
        auto it = kv_.find(key);
        Reply(fd, it == kv_.end() ? "NONE" : "OK " + it->second);
      } else if (cmd == "HEALTH") {
        long lag = 0;
        iss >> lag;  // optional: >0 also excludes slow-but-alive stragglers
        Reply(fd, Health(lag));
      } else if (cmd == "PROGRESS") {
        Reply(fd, Progress());
      } else if (cmd == "AGES") {
        Reply(fd, Ages());
      } else if (cmd == "TIME") {
        // Clock reference for NTP-style offset estimation: the server's
        // system (epoch) clock, high precision.  Workers bracket this
        // request with their own time.time() reads and take the midpoint;
        // the resulting offset aligns every worker's span timestamps onto
        // the server's timeline (tools/export_trace.py).
        std::ostringstream os;
        os.setf(std::ios::fixed);
        os.precision(6);
        os << "OK "
           << std::chrono::duration<double>(
                  std::chrono::system_clock::now().time_since_epoch())
                  .count();
        Reply(fd, os.str());
      } else if (cmd == "STATPUT") {
        // "STATPUT <task> <payload>": append an opaque stats line (the
        // rest of the line — compact JSON from the training loop) to the
        // task's bounded ring.  The ring is the live-watching data plane:
        // tools/watch_run.py polls STATDUMP against a running job without
        // touching its files.
        int task = -1;
        if (!(iss >> task)) task = -1;  // guarded: C++11 writes 0 on failure
        std::string payload;
        std::getline(iss, payload);
        if (!payload.empty() && payload[0] == ' ') payload.erase(0, 1);
        std::lock_guard<std::mutex> lock(mu_);
        if (task < 0 || task >= num_tasks_) {
          Reply(fd, "ERR statput needs a task id in range");
        } else if (HasReservedByte(payload)) {
          // The STATDUMP framing byte must be enforced HERE: a payload
          // carrying 0x1e would split into bogus entries for every
          // reader (and 0x1f would truncate their trailer parse), not
          // just the misbehaving publisher.
          Reply(fd, "ERR statput payload contains a reserved framing "
                    "byte");
        } else {
          auto& ring = stats_[task];
          StatEntry entry;
          entry.recv_time = NowSeconds();
          entry.seq = ++stat_seq_;
          entry.payload = payload;
          ring.push_back(std::move(entry));
          while (ring.size() > kStatRingCapacity) ring.pop_front();
          Reply(fd, "OK");
        }
      } else if (cmd == "STATDUMP") {
        // "STATDUMP [k]": the newest k entries (default 1) per task, one
        // response line.  Entries are separated by the ASCII record
        // separator (0x1e) — payloads are arbitrary single-line text, so
        // a printable delimiter could collide.  Each entry:
        // "<task> <age_seconds> <seq> <payload>".
        long k = 1;
        if (!(iss >> k)) k = 1;
        if (k < 1) k = 1;
        std::lock_guard<std::mutex> lock(mu_);
        double now = NowSeconds();
        std::ostringstream os;
        os.setf(std::ios::fixed);
        os.precision(3);
        os << "OK " << num_tasks_;
        for (const auto& kv : stats_) {
          const auto& ring = kv.second;
          size_t start =
              ring.size() > static_cast<size_t>(k) ? ring.size() - k : 0;
          for (size_t i = start; i < ring.size(); ++i) {
            os << '\x1e' << kv.first << ' ' << (now - ring[i].recv_time)
               << ' ' << ring[i].seq << ' ' << ring[i].payload;
          }
        }
        Reply(fd, os.str());
      } else if (cmd == "SHARDINFO") {
        // Sharded coordination plane (docs/param_exchange.md,
        // "Hierarchical exchange"): each instance of a multi-coordinator
        // deployment carries its shard identity so a router client (or an
        // operator's probe) can verify it is talking to the instance it
        // hashed a key to.  Identity is set at launch via the C ABI
        // (dtf_coord_server_set_shard, tools/coord_shard.py); a standalone
        // single-instance server reports shard=0 nshards=1.
        std::ostringstream os;
        std::lock_guard<std::mutex> lock(mu_);
        os << "OK shard=" << shard_ << " nshards=" << nshards_ << " role="
           << (is_primary_.load() ? "primary" : "standby");
        Reply(fd, os.str());
      } else if (cmd == "MEMBERS") {
        Reply(fd, Members());
      } else if (cmd == "RECONFIGURE") {
        // "RECONFIGURE" alone forces a lease scan and returns the
        // authoritative (epoch, active ids); "RECONFIGURE <task> <0|1>"
        // additionally evicts (0) or admits (1) the task explicitly — the
        // chief-driven resize path.  Guarded extraction: a failed read
        // must restore the "no argument" sentinel (C++11 writes 0 on
        // failure — which would silently evict task 0).
        int task = -1, want = -1;
        if (!(iss >> task)) task = -1;
        if (!(iss >> want)) want = -1;
        Reply(fd, Reconfigure(task, want));
      } else if (cmd == "LEAVE") {
        // Guarded extraction + bounds check: a malformed LEAVE must not
        // value-initialize task to 0 (C++11) and evict the chief, nor
        // create spurious task entries past num_tasks.
        int task = -1;
        if (!(iss >> task)) task = -1;
        std::lock_guard<std::mutex> lock(mu_);
        if (task < 0 || task >= num_tasks_) {
          Reply(fd, "ERR leave needs a task id in range");
        } else {
          TaskInfo& info = tasks_[task];
          info.registered = false;
          AppendReplLocked("R " + std::to_string(task) + " " +
                           std::to_string(info.incarnation) + " " +
                           std::to_string(info.restarts) + " 0");
          // A voluntary departure shrinks the active set immediately — no
          // lease wait — so surviving barriers/masks resize within one
          // membership poll instead of one heartbeat timeout.
          DeactivateLocked(task);
          Reply(fd, "OK");
        }
      } else if (cmd == "INFO") {
        std::ostringstream os;
        std::lock_guard<std::mutex> lock(mu_);
        UpdateMembershipLocked(NowSeconds());
        int reg = 0;
        for (auto& kv : tasks_)
          if (kv.second.registered) ++reg;
        os << "OK num_tasks=" << num_tasks_ << " registered=" << reg
           << " evictions=" << evictions_ << " epoch=" << membership_epoch_
           << " active=" << (num_tasks_ - static_cast<int>(inactive_.size()));
        // Coordinator-HA view (docs/fault_tolerance.md, "Coordinator
        // HA"): role, generation, standby count, and replication lag in
        // RECORDS — on a standby, how far behind the primary's last
        // known sequence it is; on a primary, how far behind the most
        // caught-up standby is (-1 = standby-less, the degraded state
        // tools/coord_shard.py --status and watch_run surface).
        long lag = -1;
        if (is_primary_.load()) {
          PruneStandbysLocked(NowSeconds());
          long best = -1;
          for (const auto& ack : standby_acks_)
            if (ack.second.acked > best) best = ack.second.acked;
          if (best >= 0) lag = repl_seq_ - best < 0 ? 0 : repl_seq_ - best;
        } else {
          lag = primary_latest_known_ - applied_seq_;
          if (lag < 0) lag = 0;
        }
        os << " role=" << (is_primary_.load() ? "primary" : "standby")
           << " generation=" << generation_
           << " standbys=" << standby_acks_.size() << " repl_lag=" << lag
           << " repl_applied="
           << (is_primary_.load() ? repl_seq_ : applied_seq_)
           << " repl_checksum_errors=" << repl_checksum_errors_;
        os.setf(std::ios::fixed);
        os.precision(3);
        os << " last_promotion_age_s="
           << (promoted_at_ < 0 ? -1.0 : NowSeconds() - promoted_at_);
        Reply(fd, os.str());
      } else if (cmd == "REPLJOIN") {
        // "REPLJOIN <addr>": a standby attaches (or re-attaches after
        // falling off the bounded log) and receives the snapshot
        // bootstrap — the whole state machine serialized as replication
        // records, checksummed like the stream, stamped with the current
        // sequence/generation and this standby's assigned id.  <addr> is
        // the standby's advertised endpoint ("-" = unadvertised), echoed
        // in REPLSTREAM acks so peers can size each other up at
        // promotion time.
        std::string addr;
        if (!(iss >> addr)) addr = "-";
        if (HasReservedByte(addr) || addr.find(',') != std::string::npos) {
          // The addr is echoed inside every acks= token (comma-joined,
          // 0x1e/0x1f-framed replies): a hostile one would corrupt every
          // peer's ack-table parse.
          Reply(fd, "ERR repljoin addr contains a reserved byte");
          ::close(fd);
          return;
        }
        std::lock_guard<std::mutex> lock(mu_);
        StartReplThreadLocked();  // the leadership-lease ticker
        PruneStandbysLocked(NowSeconds());
        int sid = next_standby_id_++;
        standby_acks_[sid] = {repl_seq_, addr, NowSeconds()};
        std::ostringstream os;
        os << "OK " << repl_seq_ << " " << generation_ << " "
           << lease_timeout_ << " " << sid << " " << AcksTokenLocked();
        for (const auto& body : SnapshotBodiesLocked())
          os << '\x1e' << Fnv1a(body) << ' ' << body;
        Reply(fd, os.str());
      } else if (cmd == "REPLSTREAM") {
        // "REPLSTREAM <standby_id> <from_seq>": the pull half of journal
        // streaming.  Returns every retained record in [from_seq, head]
        // (capped per batch; the standby loops until caught up), each as
        // "<seq> <fnv1a> <body>" behind an "OK <head_seq> <generation>
        // acks=<id>:<acked>:<addr>,..." header.  The from_seq doubles as
        // the standby's ack (everything below it was applied), which is
        // what "most-caught-up standby promotes" is decided on.
        int sid = -1;
        long from = 0;
        if (!(iss >> sid)) sid = -1;
        if (!(iss >> from)) from = 0;
        std::lock_guard<std::mutex> lock(mu_);
        auto it = standby_acks_.find(sid);
        if (sid < 0 || from < 1 || it == standby_acks_.end()) {
          // Unknown id (a primary restart forgot its standbys): the
          // standby must REPLJOIN again and re-bootstrap.
          Reply(fd, "ERR rejoin");
        } else if (!repl_log_.empty() && from < repl_log_.front().seq &&
                   from <= repl_seq_) {
          // Fell off the bounded log: a resync (snapshot) is cheaper
          // than replaying history we no longer hold.
          Reply(fd, "ERR resync");
        } else {
          it->second.acked = from - 1;
          it->second.last_seen = NowSeconds();
          PruneStandbysLocked(NowSeconds());
          std::ostringstream os;
          os << "OK " << repl_seq_ << " " << generation_ << " "
             << AcksTokenLocked();
          long sent = 0;
          for (const auto& rec : repl_log_) {
            if (rec.seq < from) continue;
            if (++sent > kReplBatch) break;
            os << '\x1e' << rec.seq << ' ' << Fnv1a(rec.body) << ' '
               << rec.body;
          }
          Reply(fd, os.str());
        }
      } else if (cmd == "CHAOS") {
        // Server-side fault injection (tests/ops): "CHAOS drop N" drops the
        // next N requests, "CHAOS dropfor SECS" drops everything in a time
        // window, "CHAOS delay SECS N" delays the next N responses,
        // "CHAOS off" disarms.
        std::string sub;
        iss >> sub;
        std::lock_guard<std::mutex> lock(mu_);
        if (sub == "drop") {
          long n = 0;
          iss >> n;
          chaos_drop_ = n;
          Reply(fd, "OK");
        } else if (sub == "dropfor") {
          double secs = 0;
          iss >> secs;
          chaos_drop_until_ = NowSeconds() + secs;
          Reply(fd, "OK");
        } else if (sub == "delay") {
          double secs = 0;
          long n = 0;
          iss >> secs >> n;
          chaos_delay_secs_ = secs;
          chaos_delay_ = n;
          Reply(fd, "OK");
        } else if (sub == "off") {
          chaos_drop_ = 0;
          chaos_drop_until_ = 0.0;
          chaos_delay_ = 0;
          chaos_delay_secs_ = 0.0;
          Reply(fd, "OK");
        } else {
          Reply(fd, "ERR unknown chaos directive");
        }
      } else {
        Reply(fd, "ERR unknown command");
      }
    }
    ::close(fd);
  }

  // --- Elastic membership (all callers hold mu_) -----------------------
  //
  // Active set = [0, num_tasks) minus inactive_.  Tasks start
  // presumed-active so bring-up still waits for the full cluster; only an
  // observed departure (lease expiry, LEAVE, explicit RECONFIGURE evict)
  // shrinks the set, and only REGISTER / RECONFIGURE admit grows it back.

  // Remove a task from the active set; bumps the epoch and wakes barrier
  // waiters (the departed member may have been the last arrival missing).
  void DeactivateLocked(int task) {
    if (task < 0 || task >= num_tasks_) return;
    if (inactive_.insert(task).second) {
      membership_epoch_++;
      AppendReplLocked(MembershipBodyLocked());
      barrier_cv_.notify_all();
    }
  }

  void ActivateLocked(int task) {
    if (task < 0 || task >= num_tasks_) return;
    if (inactive_.erase(task) > 0) {
      membership_epoch_++;
      AppendReplLocked(MembershipBodyLocked());
      barrier_cv_.notify_all();
    }
  }

  // The replicated membership transition: epoch + the full active set
  // ("-" when everyone is out) — small, and self-contained enough that a
  // standby can apply it without having seen the shrink/grow history.
  std::string MembershipBodyLocked() const {
    std::ostringstream os;
    os << "M " << membership_epoch_ << " ";
    bool any = false;
    for (int t = 0; t < num_tasks_; ++t) {
      if (inactive_.count(t)) continue;
      if (any) os << ',';
      os << t;
      any = true;
    }
    if (!any) os << '-';
    return os.str();
  }

  // Lease scan: any registered task silent past heartbeat_timeout_ loses
  // its lease — counted as an eviction (once per silence episode, the
  // INFO/telemetry signal) and removed from the active set (the epoch
  // signal).  Run lazily from every membership-sensitive entry point
  // (HEALTH, MEMBERS, RECONFIGURE, INFO, barrier arrivals and the sliced
  // barrier wait), so expiry is noticed within a barrier wait slice.
  void UpdateMembershipLocked(double now) {
    if (heartbeat_timeout_ <= 0) return;
    // A standby observes no heartbeats (workers talk to the primary), so
    // a local lease scan would evict everyone off stale timestamps and
    // fork the replicated membership: the stream is its only authority.
    if (!is_primary_.load()) return;
    for (auto& kv : tasks_) {
      TaskInfo& info = kv.second;
      if (!info.registered) continue;
      if ((now - info.last_heartbeat) < heartbeat_timeout_) continue;
      if (!info.evicted) {
        info.evicted = true;
        evictions_++;
      }
      DeactivateLocked(kv.first);
    }
  }

  // True when every active task has arrived (arrivals from inactive tasks
  // ride along; an empty active set releases trivially — the degenerate
  // everyone-evicted case must not deadlock the last caller).
  bool BarrierCompleteLocked(const BarrierState& b) const {
    for (int t = 0; t < num_tasks_; ++t) {
      if (inactive_.count(t)) continue;
      if (!b.arrived.count(t)) return false;
    }
    return true;
  }

  std::string Members() {
    std::lock_guard<std::mutex> lock(mu_);
    UpdateMembershipLocked(NowSeconds());
    return MembersLocked();
  }

  std::string MembersLocked() const {
    std::ostringstream os;
    os << "OK " << membership_epoch_;
    for (int t = 0; t < num_tasks_; ++t)
      if (!inactive_.count(t)) os << " " << t;
    return os.str();
  }

  std::string Reconfigure(int task, int want) {
    std::lock_guard<std::mutex> lock(mu_);
    UpdateMembershipLocked(NowSeconds());
    if (task >= 0) {
      if (task >= num_tasks_) return "ERR task out of range";
      if (want == 0)
        DeactivateLocked(task);
      else if (want == 1)
        ActivateLocked(task);
      else
        return "ERR reconfigure wants 0 (evict) or 1 (admit)";
    }
    return MembersLocked();
  }

  std::string Register(int task, long incarnation) {
    std::lock_guard<std::mutex> lock(mu_);
    TaskInfo& info = tasks_[task];
    double now = NowSeconds();
    // Lease expiry: a registered task that went a full heartbeat_timeout
    // without beating has lost its lease.  Re-registration after expiry is
    // a REJOIN even with the same incarnation (a frozen process thawing
    // out), so the caller learns it must restore-and-re-enter rather than
    // assume continuity.
    bool lease_expired = info.registered && heartbeat_timeout_ > 0 &&
                         (now - info.last_heartbeat) >= heartbeat_timeout_;
    if (info.registered && (info.incarnation != incarnation || lease_expired)) {
      // Same task id, new incarnation (a restarted worker re-joining — the
      // reference's Supervisor re-entry path, distributed.py:125, §3.4) or
      // the same incarnation returning past its lease.
      info.restarts++;
    }
    if (info.incarnation != incarnation || lease_expired) {
      // Forget the old life's progress so the rejoiner isn't instantly
      // classed a straggler before its first report.
      info.last_step = -1;
    }
    info.incarnation = incarnation;
    info.registered = true;
    info.evicted = false;
    info.last_heartbeat = now;
    AppendReplLocked("R " + std::to_string(task) + " " +
                     std::to_string(incarnation) + " " +
                     std::to_string(info.restarts) + " 1");
    // Registration is the (only) grow path: a rejoining incarnation —
    // restart, thawed freeze, or a worker returning from LEAVE — re-enters
    // the active set and bumps the membership epoch.
    ActivateLocked(task);
    std::ostringstream os;
    os << "OK " << num_tasks_ << " restarts=" << info.restarts
       << " epoch=" << membership_epoch_;
    return os.str();
  }

  void Heartbeat(int task, long step) {
    std::lock_guard<std::mutex> lock(mu_);
    TaskInfo& info = tasks_[task];
    info.last_heartbeat = NowSeconds();
    info.evicted = false;  // a live beat restores the lease
    if (step >= 0 && step > info.last_step) info.last_step = step;
  }

  // Release a complete barrier (caller holds mu_): every arrived call's
  // nonce is marked done — and streamed to standbys — BEFORE the
  // generation bumps, so a promoted standby re-answers any in-flight
  // arrival whose OK died with the old primary instead of entering it
  // into the next generation (the never-double-release rule).
  void ReleaseBarrierLocked(const std::string& name, BarrierState& b) {
    for (int t : b.arrived) {
      auto it = b.arrival_nonce.find(t);
      if (it != b.arrival_nonce.end() && it->second != 0) {
        b.done_nonce[t] = it->second;
        AppendReplLocked("N " + name + " " + std::to_string(t) + " " +
                         std::to_string(it->second));
      }
    }
    b.arrived.clear();
    b.arrival_nonce.clear();
    b.generation++;
    AppendReplLocked("B " + name + " " + std::to_string(b.generation));
    barrier_cv_.notify_all();
  }

  std::string Barrier(const std::string& name, int task, double timeout,
                      long nonce) {
    std::unique_lock<std::mutex> lock(mu_);
    BarrierState& b = barriers_[name];
    if (nonce != 0) {
      auto it = b.done_nonce.find(task);
      if (it != b.done_nonce.end() && it->second == nonce) {
        // This exact call already crossed the barrier; its OK was lost on
        // the wire and the client retried.  Re-answer, don't re-arrive.
        return "OK";
      }
    }
    long my_generation = b.generation;
    b.arrived.insert(task);
    if (nonce != 0) b.arrival_nonce[task] = nonce;
    tasks_[task].last_heartbeat = NowSeconds();
    // Elastic release: the barrier gates on the ACTIVE set, not num_tasks —
    // run the lease scan first so an arrival right after a worker died
    // releases the survivors immediately instead of stalling to timeout.
    UpdateMembershipLocked(NowSeconds());
    if (BarrierCompleteLocked(b)) {
      ReleaseBarrierLocked(name, b);
      return "OK";
    }
    auto deadline = Clock::now() + std::chrono::duration<double>(timeout);
    // Sliced waits: wake every fraction of the heartbeat timeout to re-run
    // the lease scan, so a member dying MID-wait releases the survivors
    // within one slice (the elastic no-stall property) rather than only
    // when its lease expiry happens to coincide with an arrival.
    double slice = heartbeat_timeout_ > 0 ? heartbeat_timeout_ / 4.0 : 0.25;
    if (slice > 1.0) slice = 1.0;
    if (slice < 0.02) slice = 0.02;
    while (true) {
      // Re-look-up: rehashing is impossible (std::map), but the barrier may
      // have been released and re-armed while we waited.
      BarrierState& cur = barriers_[name];
      if (cur.generation != my_generation) {
        cur.done_nonce[task] = nonce;
        return "OK";
      }
      if (shutting_down_) return "ERR shutdown";
      UpdateMembershipLocked(NowSeconds());
      if (BarrierCompleteLocked(cur)) {
        // A departure completed the barrier for the survivors; this waiter
        // performs the release on everyone's behalf.
        ReleaseBarrierLocked(name, cur);
        return "OK";
      }
      auto wake = Clock::now() + std::chrono::duration<double>(slice);
      bool final_slice = wake >= deadline;
      if (final_slice) wake = deadline;
#ifdef DTF_SANITIZER_TIMEDWAIT
      // Sanitizer-build compat (set by the Makefile tsan/asan targets,
      // docs/static_analysis.md): libstdc++ maps steady-clock waits
      // onto pthread_cond_clockwait, which gcc-10's libtsan does not
      // intercept — the checked build then reports phantom double-
      // locks/races because it never sees the unlock inside the wait.
      // The system-clock overload maps onto the intercepted
      // pthread_cond_timedwait.  Checked builds only: a wall-clock
      // step during a wait can mis-size that one slice by the step
      // size, so production keeps the steady-clock wait below.
      auto wake_point =
          std::chrono::system_clock::now() +
          std::chrono::duration_cast<std::chrono::microseconds>(
              wake - Clock::now());
#else
      auto wake_point = wake;
#endif
      if (barrier_cv_.wait_until(lock, wake_point) ==
              std::cv_status::timeout &&
          final_slice) {
        BarrierState& cur2 = barriers_[name];
        if (cur2.generation != my_generation) {
          cur2.done_nonce[task] = nonce;
          return "OK";
        }
        UpdateMembershipLocked(NowSeconds());
        if (BarrierCompleteLocked(cur2)) {
          ReleaseBarrierLocked(name, cur2);
          return "OK";
        }
        cur2.arrived.erase(task);
        cur2.arrival_nonce.erase(task);
        return "ERR barrier_timeout";
      }
    }
  }

  std::string Health(long lag) {
    std::lock_guard<std::mutex> lock(mu_);
    double now = NowSeconds();
    // Lease scan first: eviction counting (and the membership-epoch shrink)
    // lives in UpdateMembershipLocked — one detection path for HEALTH,
    // MEMBERS, barriers, and INFO alike.
    UpdateMembershipLocked(now);
    // Front-runner step among live, progress-reporting tasks: the straggler
    // criterion ("more than `lag` steps behind") is relative to it, so the
    // fastest live task is never excluded and the set can't go empty.
    long max_step = -1;
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      if (it == tasks_.end() || !it->second.registered) continue;
      if ((now - it->second.last_heartbeat) >= heartbeat_timeout_) continue;
      if (it->second.last_step > max_step) max_step = it->second.last_step;
    }
    std::ostringstream os;
    os << "OK";
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      bool alive = it != tasks_.end() && it->second.registered &&
                   (now - it->second.last_heartbeat) < heartbeat_timeout_;
      if (alive && lag > 0 && it->second.last_step >= 0 &&
          max_step - it->second.last_step > lag) {
        // Slow-but-heartbeating straggler: excluded from the live set until
        // it catches back up (reference R-of-N drop, distributed.py:97-100).
        alive = false;
      }
      os << " " << (alive ? 1 : 0);
    }
    return os.str();
  }

  std::string Progress() {
    std::lock_guard<std::mutex> lock(mu_);
    std::ostringstream os;
    os << "OK";
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      os << " " << (it == tasks_.end() ? -1 : it->second.last_step);
    }
    return os.str();
  }

  // Seconds since each task's last heartbeat (-1 = never heartbeated /
  // not registered) — the raw signal behind Health()'s boolean, exported
  // so the telemetry stream can show a straggler *approaching* the
  // timeout instead of only the eventual liveness flip.
  std::string Ages() {
    std::lock_guard<std::mutex> lock(mu_);
    double now = NowSeconds();
    std::ostringstream os;
    os << "OK";
    os.setf(std::ios::fixed);
    os.precision(3);
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      bool seen = it != tasks_.end() && it->second.registered &&
                  it->second.last_heartbeat > 0.0;
      if (seen)
        os << " " << (now - it->second.last_heartbeat);
      else
        os << " -1";
    }
    return os.str();
  }

  // --- Coordinator HA: replication log, standby pull loop, promotion ---

  // Append one state transition to the bounded in-memory replication log
  // (caller holds mu_).  The log is the standby's journal stream; a
  // standby that falls off the retained window re-bootstraps via
  // REPLJOIN, so the cap bounds memory, not correctness.
  void AppendReplLocked(const std::string& body) {
    ReplRecord rec;
    rec.seq = ++repl_seq_;
    rec.body = body;
    repl_log_.push_back(std::move(rec));
    while (repl_log_.size() > kReplLogCapacity) repl_log_.pop_front();
  }

  // The whole state machine as replication-record bodies (caller holds
  // mu_): the REPLJOIN snapshot bootstrap.  Applying these onto an empty
  // standby reproduces exactly the state an incremental stream would
  // have built.
  std::vector<std::string> SnapshotBodiesLocked() const {
    std::vector<std::string> out;
    for (const auto& e : kv_) out.push_back("K " + e.first + " " + e.second);
    for (const auto& t : tasks_)
      out.push_back("R " + std::to_string(t.first) + " " +
                    std::to_string(t.second.incarnation) + " " +
                    std::to_string(t.second.restarts) + " " +
                    (t.second.registered ? "1" : "0"));
    out.push_back(MembershipBodyLocked());
    for (const auto& b : barriers_) {
      out.push_back("B " + b.first + " " +
                    std::to_string(b.second.generation));
      for (const auto& n : b.second.done_nonce)
        out.push_back("N " + b.first + " " + std::to_string(n.first) +
                      " " + std::to_string(n.second));
    }
    out.push_back("G " + std::to_string(generation_));
    return out;
  }

  void StartReplThreadLocked() {
    if (repl_thread_started_) return;
    repl_thread_started_ = true;
    repl_thread_ = std::thread([this] { ReplLoop(); });
  }

  // The ack table as the "acks=<id>:<acked>:<addr>,..." wire token
  // (caller holds mu_), shared by the REPLJOIN and REPLSTREAM reply
  // heads: a standby that only ever bootstrapped (its primary died
  // before its first incremental poll) must STILL know its peers, or
  // at promotion time it has nobody to defer to / adopt and races its
  // sibling into a same-generation split brain.
  std::string AcksTokenLocked() const {
    std::ostringstream os;
    os << "acks=";
    bool first = true;
    for (const auto& ack : standby_acks_) {
      if (!first) os << ',';
      first = false;
      os << ack.first << ':' << ack.second.acked << ':'
         << (ack.second.addr.empty() ? "-" : ack.second.addr);
    }
    return os.str();
  }

  // Drop standbys that stopped polling (caller holds mu_): 2x the lease
  // is several poll intervals past dead.  Keeps INFO's standby count —
  // and the DEGRADED(no standby) operator signal derived from it —
  // honest across standby churn, and bounds the ack table against a
  // flapping standby re-bootstrapping under fresh ids.
  void PruneStandbysLocked(double now) {
    for (auto it = standby_acks_.begin(); it != standby_acks_.end();) {
      if (now - it->second.last_seen > 2.0 * lease_timeout_)
        it = standby_acks_.erase(it);
      else
        ++it;
    }
  }

  double ReplIntervalSeconds() const {
    double interval = lease_timeout_ / 4.0;
    if (interval > 0.5) interval = 0.5;
    if (interval < 0.02) interval = 0.02;
    return interval;
  }

  static bool ParseAddr(const std::string& addr, std::string* host,
                        int* port) {
    auto pos = addr.rfind(':');
    if (pos == std::string::npos) return false;
    *host = addr.substr(0, pos);
    *port = std::atoi(addr.c_str() + pos + 1);
    return !host->empty() && *port > 0;
  }

  // One thread serves both roles: a primary ticks its leadership lease
  // into the stream (standbys read fresh records as proof of leadership)
  // and prunes dead standbys off its ack table; a standby pulls,
  // applies, and watches the lease — switching to the primary behavior
  // the moment it promotes.  The pull target is re-read every iteration:
  // adopting an already-promoted peer re-points primary_addr_ mid-loop.
  void ReplLoop() {
    {
      // Peers reach this standby at its advertised address (echoed in
      // REPLSTREAM ack tables; what a deferring peer probes at
      // promotion time).  Default: loopback + our bound port — right
      // whenever the standby set shares a host; cross-host operators
      // pass an explicit advertise address.
      std::lock_guard<std::mutex> lock(mu_);
      if (advertise_addr_.empty())
        advertise_addr_ = "127.0.0.1:" + std::to_string(port_);
    }
    while (running_.load()) {
      if (is_primary_.load()) {
        std::lock_guard<std::mutex> lock(mu_);
        AppendReplLocked("L 1");
        PruneStandbysLocked(NowSeconds());
      } else {
        PullOnce();
        MaybePromote();
      }
      auto until = Clock::now() +
                   std::chrono::duration<double>(ReplIntervalSeconds());
      while (running_.load() && Clock::now() < until)
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  }

  // Strip the generation/role reply trailer off a raw wire response.
  static std::string StripTrailer(const std::string& resp) {
    auto cut = resp.rfind('\x1f');
    if (cut == std::string::npos) return resp;
    return resp.substr(0, cut);
  }

  double ReplRequestTimeout() const {
    double t = lease_timeout_ / 2.0;
    if (t > 1.0) t = 1.0;
    if (t < 0.2) t = 0.2;
    return t;
  }

  void PullOnce() {
    double req_timeout = ReplRequestTimeout();
    int my_id;
    long from;
    std::string target, advertise;
    {
      std::lock_guard<std::mutex> lock(mu_);
      my_id = standby_id_;
      from = applied_seq_ + 1;
      target = primary_addr_;
      advertise = advertise_addr_;
    }
    std::string host;
    int pport = 0;
    if (!ParseAddr(target, &host, &pport)) return;
    dtf::CoordClient client(host, pport, /*task_id=*/-1);
    std::string resp;
    if (my_id < 0) {
      if (!client.Request("REPLJOIN " + advertise, &resp, req_timeout))
        return;
      ApplySnapshot(StripTrailer(resp));
      return;
    }
    std::ostringstream req;
    req << "REPLSTREAM " << my_id << " " << from;
    if (!client.Request(req.str(), &resp, req_timeout)) return;
    resp = StripTrailer(resp);
    if (resp.rfind("ERR", 0) == 0) {
      // "ERR rejoin" (primary restarted, forgot us) or "ERR resync" (we
      // fell off the bounded log): re-bootstrap next poll.  The primary
      // answered, so its lease stands.
      std::lock_guard<std::mutex> lock(mu_);
      standby_id_ = -1;
      last_primary_contact_ = NowSeconds();
      return;
    }
    if (resp.rfind("OK", 0) != 0) return;
    ApplyStream(resp);
  }

  // Parse the remaining "acks=..." token(s) off a reply head stream.
  static std::map<int, std::pair<long, std::string>> ParseAcks(
      std::istringstream& head) {
    std::map<int, std::pair<long, std::string>> peers;
    std::string tok;
    while (head >> tok) {
      if (tok.rfind("acks=", 0) != 0) continue;
      std::istringstream acks(tok.substr(5));
      std::string ent;
      while (std::getline(acks, ent, ',')) {
        // "<id>:<acked>:<addr>" — the addr is what MaybePromote probes
        // to adopt an already-promoted peer.
        auto c1 = ent.find(':');
        if (c1 == std::string::npos) continue;
        auto c2 = ent.find(':', c1 + 1);
        std::string addr =
            c2 == std::string::npos ? "-" : ent.substr(c2 + 1);
        peers[std::atoi(ent.substr(0, c1).c_str())] = {
            std::atol(ent.c_str() + c1 + 1), addr};
      }
    }
    return peers;
  }

  void ApplySnapshot(const std::string& resp) {
    if (resp.rfind("OK", 0) != 0) return;
    std::vector<std::string> chunks = SplitRecords(resp);
    std::istringstream head(chunks[0]);
    std::string ok;
    long snap_seq = 0, gen = 0;
    double lease = 0.0;
    int sid = -1;
    if (!(head >> ok >> snap_seq >> gen >> lease >> sid)) return;
    std::map<int, std::pair<long, std::string>> peers = ParseAcks(head);
    peers.erase(sid);
    std::lock_guard<std::mutex> lock(mu_);
    kv_.clear();
    tasks_.clear();
    barriers_.clear();
    inactive_.clear();
    for (size_t i = 1; i < chunks.size(); ++i) {
      auto sp = chunks[i].find(' ');
      if (sp == std::string::npos) continue;
      std::string checksum = chunks[i].substr(0, sp);
      std::string body = chunks[i].substr(sp + 1);
      if (Fnv1a(body) != checksum) {
        // A torn snapshot must not half-apply: reset to a blank,
        // provably-unbootstrapped state (applied_seq_ 0 + standby_id_
        // -1 keep MaybePromote from ever serving the partial copy) and
        // re-REPLJOIN next poll.  The primary DID answer, so its lease
        // stands — without the contact refresh, a primary death inside
        // this window would promote a standby missing registrations and
        // barrier done-nonces.
        repl_checksum_errors_++;
        kv_.clear();
        tasks_.clear();
        barriers_.clear();
        inactive_.clear();
        applied_seq_ = 0;
        primary_latest_known_ = 0;
        standby_id_ = -1;
        last_primary_contact_ = NowSeconds();
        return;
      }
      ApplyRecordLocked(body);
    }
    standby_id_ = sid;
    applied_seq_ = snap_seq;
    primary_latest_known_ = snap_seq;
    generation_ = gen > generation_ ? gen : generation_;
    gen_atomic_.store(generation_);
    peer_acks_ = std::move(peers);
    promote_defers_ = 0;
    last_primary_contact_ = NowSeconds();
  }

  void ApplyStream(const std::string& resp) {
    std::vector<std::string> chunks = SplitRecords(resp);
    std::istringstream head(chunks[0]);
    std::string ok;
    long latest = 0, gen = 0;
    if (!(head >> ok >> latest >> gen)) return;
    std::map<int, std::pair<long, std::string>> peers = ParseAcks(head);
    std::lock_guard<std::mutex> lock(mu_);
    peers.erase(standby_id_);
    for (size_t i = 1; i < chunks.size(); ++i) {
      std::istringstream rec(chunks[i]);
      long seq = 0;
      std::string checksum;
      if (!(rec >> seq >> checksum)) continue;
      std::string body;
      std::getline(rec, body);
      if (!body.empty() && body[0] == ' ') body.erase(0, 1);
      if (Fnv1a(body) != checksum) {
        // Corrupt record: stop the batch here; the next poll re-requests
        // from applied_seq_ + 1 (sequence numbering makes this safe).
        repl_checksum_errors_++;
        break;
      }
      if (seq != applied_seq_ + 1) {
        // A gap means the log was trimmed between header and read — the
        // resync path will catch us up from a snapshot.
        if (seq > applied_seq_ + 1) standby_id_ = -1;
        continue;
      }
      ApplyRecordLocked(body);
      applied_seq_ = seq;
    }
    primary_latest_known_ = latest > applied_seq_ ? latest : applied_seq_;
    if (gen > generation_) {
      generation_ = gen;
      gen_atomic_.store(generation_);
    }
    peer_acks_ = std::move(peers);
    promote_defers_ = 0;
    last_primary_contact_ = NowSeconds();
  }

  static std::vector<std::string> SplitRecords(const std::string& resp) {
    std::vector<std::string> out;
    size_t start = 0;
    while (true) {
      size_t sep = resp.find('\x1e', start);
      out.push_back(resp.substr(start, sep == std::string::npos
                                           ? std::string::npos
                                           : sep - start));
      if (sep == std::string::npos) break;
      start = sep + 1;
    }
    return out;
  }

  // Apply one replicated state transition (caller holds mu_) — the same
  // state machine the primary's handlers mutate, driven from the stream.
  void ApplyRecordLocked(const std::string& body) {
    std::istringstream is(body);
    std::string type;
    if (!(is >> type)) return;
    if (type == "K") {
      std::string key, value;
      is >> key;
      std::getline(is, value);
      if (!value.empty() && value[0] == ' ') value.erase(0, 1);
      kv_[key] = value;
      AppendJournal(key, value);
    } else if (type == "R") {
      int task = -1, reg = 0;
      long inc = 0;
      int restarts = 0;
      if (!(is >> task >> inc >> restarts >> reg)) return;
      if (task < 0) return;
      TaskInfo& info = tasks_[task];
      info.incarnation = inc;
      info.restarts = restarts;
      info.registered = reg != 0;
      info.last_step = -1;
      info.evicted = false;
    } else if (type == "M") {
      long epoch = 0;
      std::string ids;
      if (!(is >> epoch >> ids)) return;
      membership_epoch_ = epoch;
      inactive_.clear();
      std::set<int> active;
      if (ids != "-") {
        std::istringstream ids_in(ids);
        std::string one;
        while (std::getline(ids_in, one, ','))
          active.insert(std::atoi(one.c_str()));
      }
      for (int t = 0; t < num_tasks_; ++t)
        if (!active.count(t)) inactive_.insert(t);
    } else if (type == "B") {
      std::string name;
      long gen = 0;
      if (!(is >> name >> gen)) return;
      BarrierState& b = barriers_[name];
      b.generation = gen;
      b.arrived.clear();
      b.arrival_nonce.clear();
    } else if (type == "N") {
      std::string name;
      int task = -1;
      long nonce = 0;
      if (!(is >> name >> task >> nonce)) return;
      barriers_[name].done_nonce[task] = nonce;
    } else if (type == "G") {
      long gen = 0;
      if (!(is >> gen)) return;
      if (gen > generation_) {
        generation_ = gen;
        gen_atomic_.store(generation_);
      }
    }
    // "L" (lease renewal) carries no state — receiving it IS the signal.
  }

  void MaybePromote() {
    double now = NowSeconds();
    std::vector<std::pair<int, std::pair<long, std::string>>> peers;
    long my_gen;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (is_primary_.load()) return;
      // Never promote before a successful bootstrap: a standby that
      // never reached its primary has no state to serve ("the primary
      // was never there" is a config error, not a failover), and one
      // mid-resync (torn snapshot, trimmed log, forgotten id) holds an
      // INCOMPLETE copy it must never serve either.
      if (last_primary_contact_ <= 0.0) return;
      if (now - last_primary_contact_ < lease_timeout_) return;
      if (standby_id_ < 0) return;
      my_gen = generation_;
      for (const auto& p : peer_acks_)
        if (p.first != standby_id_) peers.push_back(p);
    }
    // Probe peers' advertised endpoints (outside mu_: this is network
    // I/O) for one that ALREADY promoted: adopting it as the new
    // primary — re-pointing the pull loop and re-bootstrapping — is the
    // only split-brain-free outcome with multiple standbys.  Without
    // this, a surviving standby keeps polling the dead address forever
    // and eventually promotes a SECOND primary at the SAME generation,
    // which no fence can tell apart.  Peers still answering as standbys
    // go into the alive set the deferral below consults.
    std::set<int> alive;
    for (const auto& p : peers) {
      if (!running_.load()) return;
      const std::string& addr = p.second.second;
      if (addr.empty() || addr == "-") continue;
      std::string host;
      int pport = 0;
      if (!ParseAddr(addr, &host, &pport)) continue;
      dtf::CoordClient probe(host, pport, /*task_id=*/-1);
      std::string resp;
      if (!probe.Request("INFO", &resp, ReplRequestTimeout())) continue;
      if (resp.find(" role=primary") == std::string::npos) {
        alive.insert(p.first);
        continue;
      }
      long peer_gen = 0;
      auto gen_at = resp.find(" generation=");
      if (gen_at != std::string::npos)
        peer_gen = std::atol(resp.c_str() + gen_at + 12);
      if (peer_gen < my_gen) continue;  // a stale ghost, not a leader
      std::lock_guard<std::mutex> lock(mu_);
      if (is_primary_.load()) return;
      primary_addr_ = addr;
      standby_id_ = -1;  // REPLJOIN the new leader next poll
      promote_defers_ = 0;
      last_primary_contact_ = NowSeconds();
      std::fprintf(stderr,
                   "coord: standby re-attached to promoted peer %s "
                   "(generation %ld)\n",
                   addr.c_str(), peer_gen);
      return;
    }
    std::lock_guard<std::mutex> lock(mu_);
    if (is_primary_.load() || standby_id_ < 0) return;
    if (now - last_primary_contact_ < lease_timeout_) return;
    // Deferral rules, in takeover-priority order:
    // - a peer AHEAD of us should take the promotion (most-caught-up
    //   rule) — deferred to a BOUNDED number of windows, because that
    //   peer may have died with the primary;
    // - a LIVE peer with a lower standby id wins ties — deferred to
    //   WITHOUT a bound, because "live" was just probed above: either
    //   it promotes within its own bounded windows (we adopt it next
    //   probe) or it dies (drops out of the alive set and we stop
    //   deferring).  The asymmetry is what keeps two survivors from
    //   exhausting identical bounds in the same window and promoting
    //   side by side.
    for (const auto& p : peer_acks_) {
      if (p.first == standby_id_) continue;
      if (p.second.first > applied_seq_ && promote_defers_ < 3) {
        promote_defers_++;
        last_primary_contact_ = now;
        return;
      }
      if (p.first < standby_id_ && alive.count(p.first)) {
        last_primary_contact_ = now;
        return;
      }
    }
    PromoteLocked(now);
  }

  // Lease expired: this standby takes over (caller holds mu_).  The
  // coordinator generation bumps and persists (the split-brain fence: a
  // restarted old primary keeps its dead generation and every reply it
  // sends is fenced client-side); barriers keep their replicated
  // generations and done-nonces (in-flight arrivals are re-answered,
  // never double-released); every registered task is PRESUMED ACTIVE
  // with a fresh lease, exactly like bring-up, until the first heartbeat
  // round re-establishes real leases.
  void PromoteLocked(double now) {
    is_primary_.store(true);
    generation_++;
    gen_atomic_.store(generation_);
    promoted_at_ = now;
    PersistMetaLocked();
    AppendReplLocked("G " + std::to_string(generation_));
    for (auto& t : tasks_) {
      if (!t.second.registered) continue;
      t.second.last_heartbeat = now;
      t.second.evicted = false;
    }
    if (!inactive_.empty()) {
      inactive_.clear();
      membership_epoch_++;
      AppendReplLocked(MembershipBodyLocked());
    }
    standby_acks_.clear();
    next_standby_id_ = 0;
    barrier_cv_.notify_all();
    std::fprintf(stderr,
                 "coord: standby promoted to primary (generation %ld, "
                 "%ld records applied)\n",
                 generation_, applied_seq_);
  }

  // Generation persistence (<persist_path>.meta, atomic rename): the
  // half of the leadership lease that must survive a restart so a
  // revived process can never serve an older generation than it already
  // held.  In-memory only when no persist path is configured.
  void LoadMeta() {
    if (persist_path_.empty()) return;
    std::ifstream in(persist_path_ + ".meta");
    std::string key;
    long value = 0;
    while (in >> key >> value)
      if (key == "generation" && value > generation_) generation_ = value;
  }

  void PersistMetaLocked() {
    if (persist_path_.empty()) return;
    std::string tmp = persist_path_ + ".meta.tmp";
    std::FILE* f = std::fopen(tmp.c_str(), "w");
    if (f == nullptr) return;
    std::fprintf(f, "generation %ld\n", generation_);
    std::fflush(f);
    std::fclose(f);
    std::rename(tmp.c_str(), (persist_path_ + ".meta").c_str());
  }

  // --- KV persistence: "key value" lines, last-wins replay, compacted on
  // load.  Only the KV store persists (tasks/barriers are ephemeral by
  // design: incarnations re-register, barriers re-form).
  void LoadJournal() {
    std::ifstream in(persist_path_);
    if (in) {
      std::string line;
      while (std::getline(in, line)) {
        if (line.empty()) continue;
        auto sp = line.find(' ');
        if (sp == std::string::npos)
          kv_[line] = "";
        else
          kv_[line.substr(0, sp)] = line.substr(sp + 1);
      }
      in.close();
    }
    // Compact: rewrite current state, then append from there.
    std::string tmp = persist_path_ + ".tmp";
    std::FILE* f = std::fopen(tmp.c_str(), "w");
    if (f == nullptr) return;
    for (const auto& e : kv_)
      std::fprintf(f, "%s %s\n", e.first.c_str(), e.second.c_str());
    std::fflush(f);
    std::fclose(f);
    std::rename(tmp.c_str(), persist_path_.c_str());
    journal_ = std::fopen(persist_path_.c_str(), "a");
    journal_bytes_ = 0;
    for (const auto& e : kv_)
      journal_bytes_ += e.first.size() + e.second.size() + 2;
  }

  void AppendJournal(const std::string& key, const std::string& value) {
    // Caller holds mu_.
    if (journal_ == nullptr) return;
    std::fprintf(journal_, "%s %s\n", key.c_str(), value.c_str());
    std::fflush(journal_);
    journal_bytes_ += key.size() + value.size() + 2;
    // Steady-state compaction: async param publishes rewrite the same keys
    // every sync period, so the append-only journal dwarfs the live map.
    // Rewrite once appends exceed ~4x the live size (1 MiB floor so tiny
    // stores never compact) — the threshold scales with the store, so a
    // large live KV does not trigger a full rewrite on every set.
    size_t live = 0;
    for (const auto& e : kv_) live += e.first.size() + e.second.size() + 2;
    if (journal_bytes_ > (1u << 20) + 4 * live) {
      std::fclose(journal_);
      journal_ = nullptr;
      std::string tmp = persist_path_ + ".tmp";
      std::FILE* f = std::fopen(tmp.c_str(), "w");
      if (f != nullptr) {
        for (const auto& e : kv_)
          std::fprintf(f, "%s %s\n", e.first.c_str(), e.second.c_str());
        std::fflush(f);
        std::fclose(f);
        std::rename(tmp.c_str(), persist_path_.c_str());
      }
      journal_ = std::fopen(persist_path_.c_str(), "a");
      journal_bytes_ = live;
    }
  }

  int listen_fd_ = -1;
  int port_ = 0;
  int num_tasks_;
  double heartbeat_timeout_;
  std::string persist_path_;
  std::FILE* journal_ = nullptr;
  size_t journal_bytes_ = 0;
  std::atomic<bool> running_{false};
  bool shutting_down_ = false;
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::condition_variable workers_done_cv_;
  int active_handlers_ = 0;

  std::mutex mu_;
  std::condition_variable barrier_cv_;
  std::map<int, TaskInfo> tasks_;
  std::map<std::string, BarrierState> barriers_;
  std::map<std::string, std::string> kv_;
  // Live per-task stats rings (STATPUT/STATDUMP).  Bounded so a fast
  // publisher costs constant server memory; 128 entries at ~100 B each is
  // ~13 KiB/task — the watcher only ever wants the newest few.
  static constexpr size_t kStatRingCapacity = 128;
  std::map<int, std::deque<StatEntry>> stats_;
  long stat_seq_ = 0;
  long evictions_ = 0;  // expired leases observed (INFO evictions=N)
  // Elastic membership: active set = [0, num_tasks) minus inactive_; the
  // epoch increments on every shrink/grow (MEMBERS/RECONFIGURE expose it).
  std::set<int> inactive_;
  long membership_epoch_ = 1;
  // Shard identity (SHARDINFO): which instance of a sharded coordination
  // plane this server is.  Guarded by mu_ like the rest of the state.
  int shard_ = 0;
  int nshards_ = 1;
  // Armed fault injection (the CHAOS command); all guarded by mu_.
  long chaos_drop_ = 0;           // drop the next N requests
  double chaos_drop_until_ = 0.0; // drop everything until this time
  double chaos_delay_secs_ = 0.0; // delay the next chaos_delay_ responses
  long chaos_delay_ = 0;

  // --- Coordinator HA state (docs/fault_tolerance.md, "Coordinator HA").
  // primary_addr_/lease_timeout_ are fixed at construction; is_primary_
  // and gen_atomic_ are atomics because the reply trailer reads them
  // without mu_; everything else is guarded by mu_.
  std::string primary_addr_;      // standby: the leader we stream from
  double lease_timeout_ = 2.0;    // leadership lease (promotion trigger)
  std::atomic<bool> is_primary_{true};
  std::atomic<long> gen_atomic_{1};
  long generation_ = 1;           // coordinator generation (fences ghosts)
  static constexpr size_t kReplLogCapacity = 8192;
  static constexpr long kReplBatch = 512;  // records per REPLSTREAM reply
  std::deque<ReplRecord> repl_log_;
  long repl_seq_ = 0;             // head sequence number (primary side)
  // Primary side: per-standby replication bookkeeping.  last_seen drives
  // pruning: a standby that stops polling past 2x the lease is dead and
  // must stop counting toward INFO's standby count, or the operator's
  // DEGRADED(no standby) signal could never fire again after churn (and
  // a flapping standby's re-bootstraps would grow the map unboundedly).
  struct StandbyAck {
    long acked = 0;
    std::string addr;             // advertised endpoint ("-" = a tap)
    double last_seen = 0.0;
  };
  std::map<int, StandbyAck> standby_acks_;
  int next_standby_id_ = 0;
  // Standby side: stream cursor + the promotion evidence.
  int standby_id_ = -1;           // -1 = needs REPLJOIN (bootstrap/resync)
  long applied_seq_ = 0;
  long primary_latest_known_ = 0;
  double last_primary_contact_ = 0.0;  // 0 = never bootstrapped
  // Peer standbys as of the last REPLSTREAM ack table: id -> (acked
  // sequence, advertised addr).  The addrs are what a deferring standby
  // probes to ADOPT an already-promoted peer instead of promoting a
  // second primary beside it.
  std::map<int, std::pair<long, std::string>> peer_acks_;
  std::string advertise_addr_;    // how peers reach THIS standby
  int promote_defers_ = 0;
  double promoted_at_ = -1.0;     // NowSeconds() of promotion (-1 = never)
  long repl_checksum_errors_ = 0;
  std::thread repl_thread_;
  bool repl_thread_started_ = false;
};

}  // namespace dtf

// ---------------- C ABI for ctypes ----------------


extern "C" {

void* dtf_coord_server_start(int port, int num_tasks, double heartbeat_timeout,
                             const char* persist_path) {
  auto* s = new dtf::CoordServer(
      port, num_tasks, heartbeat_timeout,
      persist_path == nullptr ? std::string() : std::string(persist_path));
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

// Sharded-plane variant: shard identity is part of construction, so it is
// visible before the accept thread takes its first connection (a racing
// bring-up probe on a fixed port must never read the default identity).
// A separate symbol, not new parameters on dtf_coord_server_start, so a
// prebuilt DTF_COORD_BIN older than the sharded plane keeps loading.
void* dtf_coord_server_start2(int port, int num_tasks,
                              double heartbeat_timeout,
                              const char* persist_path, int shard,
                              int nshards) {
  auto* s = new dtf::CoordServer(
      port, num_tasks, heartbeat_timeout,
      persist_path == nullptr ? std::string() : std::string(persist_path),
      shard, nshards);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

// Coordinator-HA variant (docs/fault_tolerance.md, "Coordinator HA"):
// a non-empty primary_addr ("host:port") starts this instance as a warm
// STANDBY of that control shard — it snapshot-bootstraps via REPLJOIN,
// applies the REPLSTREAM journal stream, refuses mutating commands with
// NOTPRIMARY, and self-promotes (generation bump) when the leadership
// lease (lease_timeout seconds without primary contact) expires.  A
// separate symbol so prebuilt DTF_COORD_BIN libraries older than the HA
// plane keep loading.
void* dtf_coord_server_start3(int port, int num_tasks,
                              double heartbeat_timeout,
                              const char* persist_path, int shard,
                              int nshards, const char* primary_addr,
                              double lease_timeout,
                              const char* advertise_addr) {
  auto* s = new dtf::CoordServer(
      port, num_tasks, heartbeat_timeout,
      persist_path == nullptr ? std::string() : std::string(persist_path),
      shard, nshards,
      primary_addr == nullptr ? std::string() : std::string(primary_addr),
      lease_timeout,
      advertise_addr == nullptr ? std::string()
                                : std::string(advertise_addr));
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int dtf_coord_server_port(void* server) {
  return static_cast<dtf::CoordServer*>(server)->port();
}

void dtf_coord_server_stop(void* server) {
  auto* s = static_cast<dtf::CoordServer*>(server);
  s->Stop();
  delete s;
}

void dtf_coord_server_join(void* server) {
  static_cast<dtf::CoordServer*>(server)->Join();
}

// Shard identity for a sharded coordination plane (SHARDINFO replies
// "OK shard=<s> nshards=<n>").  Call right after start, before clients
// are pointed at the instance.
void dtf_coord_server_set_shard(void* server, int shard, int nshards) {
  static_cast<dtf::CoordServer*>(server)->SetShard(shard, nshards);
}

void* dtf_coord_client_create(const char* host, int port, int task_id) {
  return new dtf::CoordClient(host, port, task_id);
}

void dtf_coord_client_destroy(void* client) {
  delete static_cast<dtf::CoordClient*>(client);
}

// Returns response length (>=0) on success, -1 on transport failure.
// Response is NUL-terminated into out (truncated to outlen-1).
int dtf_coord_client_request(void* client, const char* line, char* out,
                             int outlen, double timeout_sec) {
  auto* c = static_cast<dtf::CoordClient*>(client);
  std::string resp;
  if (!c->Request(line, &resp, timeout_sec)) return -1;
  int n = static_cast<int>(resp.size());
  int copy = n < outlen - 1 ? n : outlen - 1;
  std::memcpy(out, resp.data(), static_cast<size_t>(copy));
  out[copy] = '\0';
  return n;
}

}  // extern "C"
