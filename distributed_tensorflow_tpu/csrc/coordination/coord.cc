// dtf-tpu coordination service — C++ control plane (N1 replacement).
//
// The reference's distributed runtime is TensorFlow's C++ gRPC server
// (reference distributed.py:54: tf.train.Server starts MasterService +
// WorkerService).  On TPU the data plane (parameter pull / gradient push)
// is gone — XLA collectives over ICI carry tensors — so the native runtime
// that remains is a control plane over DCN:
//
//   - task registration with incarnation numbers (restart detection)
//   - named barriers across all live tasks (sync-mode step gating / init)
//   - heartbeat-based health tracking with optional step progress
//     (straggler & failure detection: a slow-but-alive task that falls more
//     than a caller-chosen lag behind the front-runner is excluded from the
//     live set — the reference SyncReplicasOptimizer's R-of-N
//     stale-gradient-drop semantics, distributed.py:92-100 — and rejoins
//     automatically once it catches up; feeds the R<N replica mask of
//     parallel/sync.py)
//   - a key-value store (variable-initialized flags, checkpoint locations,
//     async-published parameters, chief election state — what the
//     reference's Supervisor asked its master for, distributed.py:125),
//     optionally journaled to disk so a restarted coordination service
//     restores it (the durability role the reference's PS held implicitly)
//   - elastic membership: a monotonically increasing *membership epoch*
//     over the active task set.  Every task starts presumed-active (so
//     bring-up still gates on num_tasks); a lease expiry or an explicit
//     LEAVE shrinks the set and bumps the epoch, a re-REGISTER grows it
//     and bumps again.  Barriers release on the ACTIVE set, not on
//     num_tasks, so survivors stop stalling behind the dead — the
//     reference's async PS mode degraded this gracefully by construction
//     (surviving workers kept pushing gradients, distributed.py:102);
//     here the same property holds for the sync path via the R<N mask.
//     MEMBERS reads (epoch, active ids); RECONFIGURE forces a lease scan
//     (and can explicitly evict/admit a task — chief-driven resizes).
//   - observability plumbing: TIME exposes the server's epoch clock so
//     workers can estimate their clock offset (NTP-style midpoint) and
//     the exported cross-worker trace aligns; STATPUT/STATDUMP keep a
//     bounded per-task ring of opaque live-stats lines so a watcher
//     (tools/watch_run.py) can see a running cluster without touching
//     its files (docs/observability.md).
//
// Wire protocol: one TCP connection per request, single request line,
// single "OK ..." / "ERR ..." / "NONE" response line.  Python binds via
// ctypes to the C ABI at the bottom (no pybind11 in the image).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace dtf {

using Clock = std::chrono::steady_clock;

static double NowSeconds() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch()).count();
}

struct TaskInfo {
  long incarnation = 0;
  double last_heartbeat = 0.0;
  long last_step = -1;  // progress carried in heartbeats; -1 = never reported
  int restarts = 0;
  bool registered = false;
  bool evicted = false;  // lease expired (heartbeat silence) since last seen
};

// One live-stats ring entry (the STATPUT/STATDUMP protocol pair): an
// opaque payload line a worker published (compact JSON from the training
// loop), stamped with the server's receipt time so readers see staleness
// without trusting worker clocks.
struct StatEntry {
  double recv_time = 0.0;  // server steady-clock receipt time
  long seq = 0;            // server-global publish sequence number
  std::string payload;
};

struct BarrierState {
  std::set<int> arrived;
  long generation = 0;  // bumped when a barrier releases, so reuse works
  // Last successfully-released call nonce per task: a transport-level
  // RETRY of an arrival whose barrier already released (response lost on
  // the wire) must return OK instead of entering the next generation.
  std::map<int, long> done_nonce;
};

class CoordServer {
 public:
  CoordServer(int port, int num_tasks, double heartbeat_timeout,
              const std::string& persist_path = "", int shard = 0,
              int nshards = 1)
      : num_tasks_(num_tasks), heartbeat_timeout_(heartbeat_timeout),
        persist_path_(persist_path), shard_(shard),
        nshards_(nshards < 1 ? 1 : nshards) {
    // Shard identity is fixed BEFORE the accept thread below spawns, so
    // no client — not even one racing bring-up on a fixed port — can
    // ever read the default identity from a sharded instance.
    if (!persist_path_.empty()) LoadJournal();
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        ::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    running_.store(true);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~CoordServer() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  // Shard identity of a sharded coordination plane (SHARDINFO).  Prefer
  // the constructor parameters (identity fixed before the accept thread
  // exists); this setter remains for callers holding an already-running
  // server.
  void SetShard(int shard, int nshards) {
    std::lock_guard<std::mutex> lock(mu_);
    shard_ = shard;
    nshards_ = nshards < 1 ? 1 : nshards;
  }

  void Stop() {
    bool expected = true;
    if (!running_.compare_exchange_strong(expected, false)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> lock(mu_);
      shutting_down_ = true;
    }
    barrier_cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    // Wait for detached handler threads (barrier waiters are woken above).
    {
      std::unique_lock<std::mutex> lock(workers_mu_);
      workers_done_cv_.wait(lock, [this] { return active_handlers_ == 0; });
    }
    std::lock_guard<std::mutex> lock(mu_);
    if (journal_ != nullptr) {
      std::fclose(journal_);
      journal_ = nullptr;
    }
  }

  void Join() {
    if (accept_thread_.joinable()) accept_thread_.join();
  }

 private:
  void AcceptLoop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      {
        std::lock_guard<std::mutex> lock(workers_mu_);
        ++active_handlers_;
      }
      std::thread([this, fd] {
        Handle(fd);
        std::lock_guard<std::mutex> lock(workers_mu_);
        if (--active_handlers_ == 0) workers_done_cv_.notify_all();
      }).detach();
    }
  }

  static bool ReadLine(int fd, std::string* out) {
    // Buffered reads: the protocol is one request line per connection, so
    // bulk recv() is safe (no bytes follow the newline) and necessary —
    // byte-at-a-time recv costs a syscall per byte, which pushed
    // chunk-scale KV values (512 KiB parameter chunks from param_sync.py)
    // past the client's request timeout.
    out->clear();
    char buf[65536];
    while (true) {
      ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
      if (n <= 0) return false;
      for (ssize_t i = 0; i < n; ++i) {
        if (buf[i] == '\n') return true;
        out->push_back(buf[i]);
      }
      // Request-line cap: KV values (async-published parameters arrive as
      // chunked entries from param_sync.py) stay well under this; the cap
      // only bounds a runaway/hostile client.
      if (out->size() > (8u << 20)) return false;
    }
  }

  static void WriteLine(int fd, const std::string& line) {
    std::string msg = line + "\n";
    size_t off = 0;
    while (off < msg.size()) {
      ssize_t n = ::send(fd, msg.data() + off, msg.size() - off, MSG_NOSIGNAL);
      if (n <= 0) return;
      off += static_cast<size_t>(n);
    }
  }

  void Handle(int fd) {
    // Bound the initial read so a client that connects and dies without
    // sending a request line can't pin this handler (and hang Stop()) forever.
    timeval tv{};
    tv.tv_sec = 30;
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    std::string line;
    if (ReadLine(fd, &line)) {
      std::istringstream iss(line);
      std::string cmd;
      iss >> cmd;
      // Fault injection (the CHAOS command below arms it): drop = close the
      // connection without a response (the client sees a transport failure
      // and exercises its retry/backoff path), delay = respond late.  CHAOS
      // itself is exempt so the harness can always disarm.
      if (cmd != "CHAOS") {
        bool drop = false;
        double delay = 0.0;
        {
          std::lock_guard<std::mutex> lock(mu_);
          if (chaos_drop_ > 0) {
            chaos_drop_--;
            drop = true;
          } else if (chaos_drop_until_ > NowSeconds()) {
            drop = true;
          } else if (chaos_delay_ > 0 && chaos_delay_secs_ > 0) {
            chaos_delay_--;
            delay = chaos_delay_secs_;
          }
        }
        if (drop) {
          ::close(fd);
          return;
        }
        if (delay > 0)
          std::this_thread::sleep_for(
              std::chrono::duration<double>(delay));
      }
      if (cmd == "REGISTER") {
        int task;
        long inc;
        iss >> task >> inc;
        WriteLine(fd, Register(task, inc));
      } else if (cmd == "HEARTBEAT") {
        int task;
        long step = -1;
        iss >> task;
        // Step is optional (liveness-only heartbeat); a failed extraction
        // writes 0 since C++11, so restore the "no report" sentinel.
        if (!(iss >> step)) step = -1;
        Heartbeat(task, step);
        WriteLine(fd, "OK");
      } else if (cmd == "BARRIER") {
        std::string name;
        int task;
        double timeout;
        long nonce = 0;  // optional per-call id (retry idempotency)
        iss >> name >> task >> timeout;
        if (!(iss >> nonce)) nonce = 0;
        WriteLine(fd, Barrier(name, task, timeout, nonce));
      } else if (cmd == "KVSET") {
        std::string key, value;
        iss >> key;
        std::getline(iss, value);
        if (!value.empty() && value[0] == ' ') value.erase(0, 1);
        {
          std::lock_guard<std::mutex> lock(mu_);
          kv_[key] = value;
          AppendJournal(key, value);
        }
        WriteLine(fd, "OK");
      } else if (cmd == "KVGET") {
        std::string key;
        iss >> key;
        std::lock_guard<std::mutex> lock(mu_);
        auto it = kv_.find(key);
        WriteLine(fd, it == kv_.end() ? "NONE" : "OK " + it->second);
      } else if (cmd == "HEALTH") {
        long lag = 0;
        iss >> lag;  // optional: >0 also excludes slow-but-alive stragglers
        WriteLine(fd, Health(lag));
      } else if (cmd == "PROGRESS") {
        WriteLine(fd, Progress());
      } else if (cmd == "AGES") {
        WriteLine(fd, Ages());
      } else if (cmd == "TIME") {
        // Clock reference for NTP-style offset estimation: the server's
        // system (epoch) clock, high precision.  Workers bracket this
        // request with their own time.time() reads and take the midpoint;
        // the resulting offset aligns every worker's span timestamps onto
        // the server's timeline (tools/export_trace.py).
        std::ostringstream os;
        os.setf(std::ios::fixed);
        os.precision(6);
        os << "OK "
           << std::chrono::duration<double>(
                  std::chrono::system_clock::now().time_since_epoch())
                  .count();
        WriteLine(fd, os.str());
      } else if (cmd == "STATPUT") {
        // "STATPUT <task> <payload>": append an opaque stats line (the
        // rest of the line — compact JSON from the training loop) to the
        // task's bounded ring.  The ring is the live-watching data plane:
        // tools/watch_run.py polls STATDUMP against a running job without
        // touching its files.
        int task = -1;
        if (!(iss >> task)) task = -1;  // guarded: C++11 writes 0 on failure
        std::string payload;
        std::getline(iss, payload);
        if (!payload.empty() && payload[0] == ' ') payload.erase(0, 1);
        std::lock_guard<std::mutex> lock(mu_);
        if (task < 0 || task >= num_tasks_) {
          WriteLine(fd, "ERR statput needs a task id in range");
        } else if (payload.find('\x1e') != std::string::npos) {
          // The STATDUMP framing byte must be enforced HERE: a payload
          // carrying 0x1e would split into bogus entries for every
          // reader, not just the misbehaving publisher.
          WriteLine(fd, "ERR statput payload contains the 0x1e separator");
        } else {
          auto& ring = stats_[task];
          StatEntry entry;
          entry.recv_time = NowSeconds();
          entry.seq = ++stat_seq_;
          entry.payload = payload;
          ring.push_back(std::move(entry));
          while (ring.size() > kStatRingCapacity) ring.pop_front();
          WriteLine(fd, "OK");
        }
      } else if (cmd == "STATDUMP") {
        // "STATDUMP [k]": the newest k entries (default 1) per task, one
        // response line.  Entries are separated by the ASCII record
        // separator (0x1e) — payloads are arbitrary single-line text, so
        // a printable delimiter could collide.  Each entry:
        // "<task> <age_seconds> <seq> <payload>".
        long k = 1;
        if (!(iss >> k)) k = 1;
        if (k < 1) k = 1;
        std::lock_guard<std::mutex> lock(mu_);
        double now = NowSeconds();
        std::ostringstream os;
        os.setf(std::ios::fixed);
        os.precision(3);
        os << "OK " << num_tasks_;
        for (const auto& kv : stats_) {
          const auto& ring = kv.second;
          size_t start =
              ring.size() > static_cast<size_t>(k) ? ring.size() - k : 0;
          for (size_t i = start; i < ring.size(); ++i) {
            os << '\x1e' << kv.first << ' ' << (now - ring[i].recv_time)
               << ' ' << ring[i].seq << ' ' << ring[i].payload;
          }
        }
        WriteLine(fd, os.str());
      } else if (cmd == "SHARDINFO") {
        // Sharded coordination plane (docs/param_exchange.md,
        // "Hierarchical exchange"): each instance of a multi-coordinator
        // deployment carries its shard identity so a router client (or an
        // operator's probe) can verify it is talking to the instance it
        // hashed a key to.  Identity is set at launch via the C ABI
        // (dtf_coord_server_set_shard, tools/coord_shard.py); a standalone
        // single-instance server reports shard=0 nshards=1.
        std::ostringstream os;
        std::lock_guard<std::mutex> lock(mu_);
        os << "OK shard=" << shard_ << " nshards=" << nshards_;
        WriteLine(fd, os.str());
      } else if (cmd == "MEMBERS") {
        WriteLine(fd, Members());
      } else if (cmd == "RECONFIGURE") {
        // "RECONFIGURE" alone forces a lease scan and returns the
        // authoritative (epoch, active ids); "RECONFIGURE <task> <0|1>"
        // additionally evicts (0) or admits (1) the task explicitly — the
        // chief-driven resize path.  Guarded extraction: a failed read
        // must restore the "no argument" sentinel (C++11 writes 0 on
        // failure — which would silently evict task 0).
        int task = -1, want = -1;
        if (!(iss >> task)) task = -1;
        if (!(iss >> want)) want = -1;
        WriteLine(fd, Reconfigure(task, want));
      } else if (cmd == "LEAVE") {
        // Guarded extraction + bounds check: a malformed LEAVE must not
        // value-initialize task to 0 (C++11) and evict the chief, nor
        // create spurious task entries past num_tasks.
        int task = -1;
        if (!(iss >> task)) task = -1;
        std::lock_guard<std::mutex> lock(mu_);
        if (task < 0 || task >= num_tasks_) {
          WriteLine(fd, "ERR leave needs a task id in range");
        } else {
          tasks_[task].registered = false;
          // A voluntary departure shrinks the active set immediately — no
          // lease wait — so surviving barriers/masks resize within one
          // membership poll instead of one heartbeat timeout.
          DeactivateLocked(task);
          WriteLine(fd, "OK");
        }
      } else if (cmd == "INFO") {
        std::ostringstream os;
        std::lock_guard<std::mutex> lock(mu_);
        UpdateMembershipLocked(NowSeconds());
        int reg = 0;
        for (auto& kv : tasks_)
          if (kv.second.registered) ++reg;
        os << "OK num_tasks=" << num_tasks_ << " registered=" << reg
           << " evictions=" << evictions_ << " epoch=" << membership_epoch_
           << " active=" << (num_tasks_ - static_cast<int>(inactive_.size()));
        WriteLine(fd, os.str());
      } else if (cmd == "CHAOS") {
        // Server-side fault injection (tests/ops): "CHAOS drop N" drops the
        // next N requests, "CHAOS dropfor SECS" drops everything in a time
        // window, "CHAOS delay SECS N" delays the next N responses,
        // "CHAOS off" disarms.
        std::string sub;
        iss >> sub;
        std::lock_guard<std::mutex> lock(mu_);
        if (sub == "drop") {
          long n = 0;
          iss >> n;
          chaos_drop_ = n;
          WriteLine(fd, "OK");
        } else if (sub == "dropfor") {
          double secs = 0;
          iss >> secs;
          chaos_drop_until_ = NowSeconds() + secs;
          WriteLine(fd, "OK");
        } else if (sub == "delay") {
          double secs = 0;
          long n = 0;
          iss >> secs >> n;
          chaos_delay_secs_ = secs;
          chaos_delay_ = n;
          WriteLine(fd, "OK");
        } else if (sub == "off") {
          chaos_drop_ = 0;
          chaos_drop_until_ = 0.0;
          chaos_delay_ = 0;
          chaos_delay_secs_ = 0.0;
          WriteLine(fd, "OK");
        } else {
          WriteLine(fd, "ERR unknown chaos directive");
        }
      } else {
        WriteLine(fd, "ERR unknown command");
      }
    }
    ::close(fd);
  }

  // --- Elastic membership (all callers hold mu_) -----------------------
  //
  // Active set = [0, num_tasks) minus inactive_.  Tasks start
  // presumed-active so bring-up still waits for the full cluster; only an
  // observed departure (lease expiry, LEAVE, explicit RECONFIGURE evict)
  // shrinks the set, and only REGISTER / RECONFIGURE admit grows it back.

  // Remove a task from the active set; bumps the epoch and wakes barrier
  // waiters (the departed member may have been the last arrival missing).
  void DeactivateLocked(int task) {
    if (task < 0 || task >= num_tasks_) return;
    if (inactive_.insert(task).second) {
      membership_epoch_++;
      barrier_cv_.notify_all();
    }
  }

  void ActivateLocked(int task) {
    if (task < 0 || task >= num_tasks_) return;
    if (inactive_.erase(task) > 0) {
      membership_epoch_++;
      barrier_cv_.notify_all();
    }
  }

  // Lease scan: any registered task silent past heartbeat_timeout_ loses
  // its lease — counted as an eviction (once per silence episode, the
  // INFO/telemetry signal) and removed from the active set (the epoch
  // signal).  Run lazily from every membership-sensitive entry point
  // (HEALTH, MEMBERS, RECONFIGURE, INFO, barrier arrivals and the sliced
  // barrier wait), so expiry is noticed within a barrier wait slice.
  void UpdateMembershipLocked(double now) {
    if (heartbeat_timeout_ <= 0) return;
    for (auto& kv : tasks_) {
      TaskInfo& info = kv.second;
      if (!info.registered) continue;
      if ((now - info.last_heartbeat) < heartbeat_timeout_) continue;
      if (!info.evicted) {
        info.evicted = true;
        evictions_++;
      }
      DeactivateLocked(kv.first);
    }
  }

  // True when every active task has arrived (arrivals from inactive tasks
  // ride along; an empty active set releases trivially — the degenerate
  // everyone-evicted case must not deadlock the last caller).
  bool BarrierCompleteLocked(const BarrierState& b) const {
    for (int t = 0; t < num_tasks_; ++t) {
      if (inactive_.count(t)) continue;
      if (!b.arrived.count(t)) return false;
    }
    return true;
  }

  std::string Members() {
    std::lock_guard<std::mutex> lock(mu_);
    UpdateMembershipLocked(NowSeconds());
    return MembersLocked();
  }

  std::string MembersLocked() const {
    std::ostringstream os;
    os << "OK " << membership_epoch_;
    for (int t = 0; t < num_tasks_; ++t)
      if (!inactive_.count(t)) os << " " << t;
    return os.str();
  }

  std::string Reconfigure(int task, int want) {
    std::lock_guard<std::mutex> lock(mu_);
    UpdateMembershipLocked(NowSeconds());
    if (task >= 0) {
      if (task >= num_tasks_) return "ERR task out of range";
      if (want == 0)
        DeactivateLocked(task);
      else if (want == 1)
        ActivateLocked(task);
      else
        return "ERR reconfigure wants 0 (evict) or 1 (admit)";
    }
    return MembersLocked();
  }

  std::string Register(int task, long incarnation) {
    std::lock_guard<std::mutex> lock(mu_);
    TaskInfo& info = tasks_[task];
    double now = NowSeconds();
    // Lease expiry: a registered task that went a full heartbeat_timeout
    // without beating has lost its lease.  Re-registration after expiry is
    // a REJOIN even with the same incarnation (a frozen process thawing
    // out), so the caller learns it must restore-and-re-enter rather than
    // assume continuity.
    bool lease_expired = info.registered && heartbeat_timeout_ > 0 &&
                         (now - info.last_heartbeat) >= heartbeat_timeout_;
    if (info.registered && (info.incarnation != incarnation || lease_expired)) {
      // Same task id, new incarnation (a restarted worker re-joining — the
      // reference's Supervisor re-entry path, distributed.py:125, §3.4) or
      // the same incarnation returning past its lease.
      info.restarts++;
    }
    if (info.incarnation != incarnation || lease_expired) {
      // Forget the old life's progress so the rejoiner isn't instantly
      // classed a straggler before its first report.
      info.last_step = -1;
    }
    info.incarnation = incarnation;
    info.registered = true;
    info.evicted = false;
    info.last_heartbeat = now;
    // Registration is the (only) grow path: a rejoining incarnation —
    // restart, thawed freeze, or a worker returning from LEAVE — re-enters
    // the active set and bumps the membership epoch.
    ActivateLocked(task);
    std::ostringstream os;
    os << "OK " << num_tasks_ << " restarts=" << info.restarts
       << " epoch=" << membership_epoch_;
    return os.str();
  }

  void Heartbeat(int task, long step) {
    std::lock_guard<std::mutex> lock(mu_);
    TaskInfo& info = tasks_[task];
    info.last_heartbeat = NowSeconds();
    info.evicted = false;  // a live beat restores the lease
    if (step >= 0 && step > info.last_step) info.last_step = step;
  }

  std::string Barrier(const std::string& name, int task, double timeout,
                      long nonce) {
    std::unique_lock<std::mutex> lock(mu_);
    BarrierState& b = barriers_[name];
    if (nonce != 0) {
      auto it = b.done_nonce.find(task);
      if (it != b.done_nonce.end() && it->second == nonce) {
        // This exact call already crossed the barrier; its OK was lost on
        // the wire and the client retried.  Re-answer, don't re-arrive.
        return "OK";
      }
    }
    long my_generation = b.generation;
    b.arrived.insert(task);
    tasks_[task].last_heartbeat = NowSeconds();
    // Elastic release: the barrier gates on the ACTIVE set, not num_tasks —
    // run the lease scan first so an arrival right after a worker died
    // releases the survivors immediately instead of stalling to timeout.
    UpdateMembershipLocked(NowSeconds());
    if (BarrierCompleteLocked(b)) {
      b.arrived.clear();
      b.generation++;
      b.done_nonce[task] = nonce;
      barrier_cv_.notify_all();
      return "OK";
    }
    auto deadline = Clock::now() + std::chrono::duration<double>(timeout);
    // Sliced waits: wake every fraction of the heartbeat timeout to re-run
    // the lease scan, so a member dying MID-wait releases the survivors
    // within one slice (the elastic no-stall property) rather than only
    // when its lease expiry happens to coincide with an arrival.
    double slice = heartbeat_timeout_ > 0 ? heartbeat_timeout_ / 4.0 : 0.25;
    if (slice > 1.0) slice = 1.0;
    if (slice < 0.02) slice = 0.02;
    while (true) {
      // Re-look-up: rehashing is impossible (std::map), but the barrier may
      // have been released and re-armed while we waited.
      BarrierState& cur = barriers_[name];
      if (cur.generation != my_generation) {
        cur.done_nonce[task] = nonce;
        return "OK";
      }
      if (shutting_down_) return "ERR shutdown";
      UpdateMembershipLocked(NowSeconds());
      if (BarrierCompleteLocked(cur)) {
        // A departure completed the barrier for the survivors; this waiter
        // performs the release on everyone's behalf.
        cur.arrived.clear();
        cur.generation++;
        cur.done_nonce[task] = nonce;
        barrier_cv_.notify_all();
        return "OK";
      }
      auto wake = Clock::now() + std::chrono::duration<double>(slice);
      bool final_slice = wake >= deadline;
      if (final_slice) wake = deadline;
#ifdef DTF_SANITIZER_TIMEDWAIT
      // Sanitizer-build compat (set by the Makefile tsan/asan targets,
      // docs/static_analysis.md): libstdc++ maps steady-clock waits
      // onto pthread_cond_clockwait, which gcc-10's libtsan does not
      // intercept — the checked build then reports phantom double-
      // locks/races because it never sees the unlock inside the wait.
      // The system-clock overload maps onto the intercepted
      // pthread_cond_timedwait.  Checked builds only: a wall-clock
      // step during a wait can mis-size that one slice by the step
      // size, so production keeps the steady-clock wait below.
      auto wake_point =
          std::chrono::system_clock::now() +
          std::chrono::duration_cast<std::chrono::microseconds>(
              wake - Clock::now());
#else
      auto wake_point = wake;
#endif
      if (barrier_cv_.wait_until(lock, wake_point) ==
              std::cv_status::timeout &&
          final_slice) {
        BarrierState& cur2 = barriers_[name];
        if (cur2.generation != my_generation) {
          cur2.done_nonce[task] = nonce;
          return "OK";
        }
        UpdateMembershipLocked(NowSeconds());
        if (BarrierCompleteLocked(cur2)) {
          cur2.arrived.clear();
          cur2.generation++;
          cur2.done_nonce[task] = nonce;
          barrier_cv_.notify_all();
          return "OK";
        }
        cur2.arrived.erase(task);
        return "ERR barrier_timeout";
      }
    }
  }

  std::string Health(long lag) {
    std::lock_guard<std::mutex> lock(mu_);
    double now = NowSeconds();
    // Lease scan first: eviction counting (and the membership-epoch shrink)
    // lives in UpdateMembershipLocked — one detection path for HEALTH,
    // MEMBERS, barriers, and INFO alike.
    UpdateMembershipLocked(now);
    // Front-runner step among live, progress-reporting tasks: the straggler
    // criterion ("more than `lag` steps behind") is relative to it, so the
    // fastest live task is never excluded and the set can't go empty.
    long max_step = -1;
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      if (it == tasks_.end() || !it->second.registered) continue;
      if ((now - it->second.last_heartbeat) >= heartbeat_timeout_) continue;
      if (it->second.last_step > max_step) max_step = it->second.last_step;
    }
    std::ostringstream os;
    os << "OK";
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      bool alive = it != tasks_.end() && it->second.registered &&
                   (now - it->second.last_heartbeat) < heartbeat_timeout_;
      if (alive && lag > 0 && it->second.last_step >= 0 &&
          max_step - it->second.last_step > lag) {
        // Slow-but-heartbeating straggler: excluded from the live set until
        // it catches back up (reference R-of-N drop, distributed.py:97-100).
        alive = false;
      }
      os << " " << (alive ? 1 : 0);
    }
    return os.str();
  }

  std::string Progress() {
    std::lock_guard<std::mutex> lock(mu_);
    std::ostringstream os;
    os << "OK";
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      os << " " << (it == tasks_.end() ? -1 : it->second.last_step);
    }
    return os.str();
  }

  // Seconds since each task's last heartbeat (-1 = never heartbeated /
  // not registered) — the raw signal behind Health()'s boolean, exported
  // so the telemetry stream can show a straggler *approaching* the
  // timeout instead of only the eventual liveness flip.
  std::string Ages() {
    std::lock_guard<std::mutex> lock(mu_);
    double now = NowSeconds();
    std::ostringstream os;
    os << "OK";
    os.setf(std::ios::fixed);
    os.precision(3);
    for (int t = 0; t < num_tasks_; ++t) {
      auto it = tasks_.find(t);
      bool seen = it != tasks_.end() && it->second.registered &&
                  it->second.last_heartbeat > 0.0;
      if (seen)
        os << " " << (now - it->second.last_heartbeat);
      else
        os << " -1";
    }
    return os.str();
  }

  // --- KV persistence: "key value" lines, last-wins replay, compacted on
  // load.  Only the KV store persists (tasks/barriers are ephemeral by
  // design: incarnations re-register, barriers re-form).
  void LoadJournal() {
    std::ifstream in(persist_path_);
    if (in) {
      std::string line;
      while (std::getline(in, line)) {
        if (line.empty()) continue;
        auto sp = line.find(' ');
        if (sp == std::string::npos)
          kv_[line] = "";
        else
          kv_[line.substr(0, sp)] = line.substr(sp + 1);
      }
      in.close();
    }
    // Compact: rewrite current state, then append from there.
    std::string tmp = persist_path_ + ".tmp";
    std::FILE* f = std::fopen(tmp.c_str(), "w");
    if (f == nullptr) return;
    for (const auto& e : kv_)
      std::fprintf(f, "%s %s\n", e.first.c_str(), e.second.c_str());
    std::fflush(f);
    std::fclose(f);
    std::rename(tmp.c_str(), persist_path_.c_str());
    journal_ = std::fopen(persist_path_.c_str(), "a");
    journal_bytes_ = 0;
    for (const auto& e : kv_)
      journal_bytes_ += e.first.size() + e.second.size() + 2;
  }

  void AppendJournal(const std::string& key, const std::string& value) {
    // Caller holds mu_.
    if (journal_ == nullptr) return;
    std::fprintf(journal_, "%s %s\n", key.c_str(), value.c_str());
    std::fflush(journal_);
    journal_bytes_ += key.size() + value.size() + 2;
    // Steady-state compaction: async param publishes rewrite the same keys
    // every sync period, so the append-only journal dwarfs the live map.
    // Rewrite once appends exceed ~4x the live size (1 MiB floor so tiny
    // stores never compact) — the threshold scales with the store, so a
    // large live KV does not trigger a full rewrite on every set.
    size_t live = 0;
    for (const auto& e : kv_) live += e.first.size() + e.second.size() + 2;
    if (journal_bytes_ > (1u << 20) + 4 * live) {
      std::fclose(journal_);
      journal_ = nullptr;
      std::string tmp = persist_path_ + ".tmp";
      std::FILE* f = std::fopen(tmp.c_str(), "w");
      if (f != nullptr) {
        for (const auto& e : kv_)
          std::fprintf(f, "%s %s\n", e.first.c_str(), e.second.c_str());
        std::fflush(f);
        std::fclose(f);
        std::rename(tmp.c_str(), persist_path_.c_str());
      }
      journal_ = std::fopen(persist_path_.c_str(), "a");
      journal_bytes_ = live;
    }
  }

  int listen_fd_ = -1;
  int port_ = 0;
  int num_tasks_;
  double heartbeat_timeout_;
  std::string persist_path_;
  std::FILE* journal_ = nullptr;
  size_t journal_bytes_ = 0;
  std::atomic<bool> running_{false};
  bool shutting_down_ = false;
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::condition_variable workers_done_cv_;
  int active_handlers_ = 0;

  std::mutex mu_;
  std::condition_variable barrier_cv_;
  std::map<int, TaskInfo> tasks_;
  std::map<std::string, BarrierState> barriers_;
  std::map<std::string, std::string> kv_;
  // Live per-task stats rings (STATPUT/STATDUMP).  Bounded so a fast
  // publisher costs constant server memory; 128 entries at ~100 B each is
  // ~13 KiB/task — the watcher only ever wants the newest few.
  static constexpr size_t kStatRingCapacity = 128;
  std::map<int, std::deque<StatEntry>> stats_;
  long stat_seq_ = 0;
  long evictions_ = 0;  // expired leases observed (INFO evictions=N)
  // Elastic membership: active set = [0, num_tasks) minus inactive_; the
  // epoch increments on every shrink/grow (MEMBERS/RECONFIGURE expose it).
  std::set<int> inactive_;
  long membership_epoch_ = 1;
  // Shard identity (SHARDINFO): which instance of a sharded coordination
  // plane this server is.  Guarded by mu_ like the rest of the state.
  int shard_ = 0;
  int nshards_ = 1;
  // Armed fault injection (the CHAOS command); all guarded by mu_.
  long chaos_drop_ = 0;           // drop the next N requests
  double chaos_drop_until_ = 0.0; // drop everything until this time
  double chaos_delay_secs_ = 0.0; // delay the next chaos_delay_ responses
  long chaos_delay_ = 0;
};

// --- Client: connection-per-request (poll semantics match the reference's
// recovery_wait_secs=1 poll loop, distributed.py:111,125). ---

class CoordClient {
 public:
  CoordClient(std::string host, int port, int task_id)
      : host_(std::move(host)), port_(port), task_id_(task_id) {}

  int task_id() const { return task_id_; }

  bool Request(const std::string& line, std::string* response,
               double timeout_sec) {
    int fd = Connect(timeout_sec);
    if (fd < 0) return false;
    std::string msg = line + "\n";
    size_t off = 0;
    while (off < msg.size()) {
      ssize_t n = ::send(fd, msg.data() + off, msg.size() - off, MSG_NOSIGNAL);
      if (n <= 0) {
        ::close(fd);
        return false;
      }
      off += static_cast<size_t>(n);
    }
    response->clear();
    // Buffered response read (one response line per connection): the
    // byte-at-a-time version made large KVGET responses pay a syscall per
    // byte and time out at chunk scale.
    char buf[65536];
    bool done = false;
    while (!done) {
      ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
      if (n <= 0) break;
      for (ssize_t i = 0; i < n; ++i) {
        if (buf[i] == '\n') {
          done = true;
          break;
        }
        response->push_back(buf[i]);
      }
    }
    ::close(fd);
    return !response->empty();
  }

 private:
  int Connect(double timeout_sec) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_str = std::to_string(port_);
    if (::getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res) != 0)
      return -1;
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0) {
      timeval tv;
      tv.tv_sec = static_cast<long>(timeout_sec);
      tv.tv_usec = static_cast<long>((timeout_sec - tv.tv_sec) * 1e6);
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      if (::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
        ::close(fd);
        fd = -1;
      }
    }
    ::freeaddrinfo(res);
    return fd;
  }

  std::string host_;
  int port_;
  int task_id_;
};

}  // namespace dtf

// ---------------- C ABI for ctypes ----------------

extern "C" {

void* dtf_coord_server_start(int port, int num_tasks, double heartbeat_timeout,
                             const char* persist_path) {
  auto* s = new dtf::CoordServer(
      port, num_tasks, heartbeat_timeout,
      persist_path == nullptr ? std::string() : std::string(persist_path));
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

// Sharded-plane variant: shard identity is part of construction, so it is
// visible before the accept thread takes its first connection (a racing
// bring-up probe on a fixed port must never read the default identity).
// A separate symbol, not new parameters on dtf_coord_server_start, so a
// prebuilt DTF_COORD_BIN older than the sharded plane keeps loading.
void* dtf_coord_server_start2(int port, int num_tasks,
                              double heartbeat_timeout,
                              const char* persist_path, int shard,
                              int nshards) {
  auto* s = new dtf::CoordServer(
      port, num_tasks, heartbeat_timeout,
      persist_path == nullptr ? std::string() : std::string(persist_path),
      shard, nshards);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int dtf_coord_server_port(void* server) {
  return static_cast<dtf::CoordServer*>(server)->port();
}

void dtf_coord_server_stop(void* server) {
  auto* s = static_cast<dtf::CoordServer*>(server);
  s->Stop();
  delete s;
}

void dtf_coord_server_join(void* server) {
  static_cast<dtf::CoordServer*>(server)->Join();
}

// Shard identity for a sharded coordination plane (SHARDINFO replies
// "OK shard=<s> nshards=<n>").  Call right after start, before clients
// are pointed at the instance.
void dtf_coord_server_set_shard(void* server, int shard, int nshards) {
  static_cast<dtf::CoordServer*>(server)->SetShard(shard, nshards);
}

void* dtf_coord_client_create(const char* host, int port, int task_id) {
  return new dtf::CoordClient(host, port, task_id);
}

void dtf_coord_client_destroy(void* client) {
  delete static_cast<dtf::CoordClient*>(client);
}

// Returns response length (>=0) on success, -1 on transport failure.
// Response is NUL-terminated into out (truncated to outlen-1).
int dtf_coord_client_request(void* client, const char* line, char* out,
                             int outlen, double timeout_sec) {
  auto* c = static_cast<dtf::CoordClient*>(client);
  std::string resp;
  if (!c->Request(line, &resp, timeout_sec)) return -1;
  int n = static_cast<int>(resp.size());
  int copy = n < outlen - 1 ? n : outlen - 1;
  std::memcpy(out, resp.data(), static_cast<size_t>(copy));
  out[copy] = '\0';
  return n;
}

}  // extern "C"
