// Sanitizer smoke driver for the coordination service (ISSUE 10,
// docs/static_analysis.md "Sanitizer builds").
//
// Compiles coord.cc together with this main() under
// -fsanitize=thread,undefined (`make -C . tsan-smoke`) and runs a REAL
// coordination session in one process: a server on an ephemeral port,
// N client threads hammering the full 16-command protocol over real
// sockets — registration, heartbeats, reused barriers, KV (including a
// chunk-scale value), STATPUT/STATDUMP, MEMBERS/RECONFIGURE, TIME,
// HEALTH/PROGRESS/AGES/INFO, CHAOS drop/recover, LEAVE — then a
// concurrent Stop().  Every handler runs on its own detached thread, so
// this exercises exactly the interleavings the mutex discipline in
// coord.cc must survive.  ThreadSanitizer exits non-zero on any data
// race; the CI leg (ci.sh) fails on that exit status.
//
// Deliberately has no gtest/argparse dependencies: build and run.

#include "coord.cc"

#include <cassert>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kTasks = 4;
constexpr int kBarrierRounds = 3;

void ClientSession(int port, int task, std::atomic<int>* failures) {
  dtf::CoordClient client("127.0.0.1", port, task);
  std::string resp;
  auto expect = [&](const std::string& line, const char* prefix) {
    if (!client.Request(line, &resp, 5.0) ||
        resp.rfind(prefix, 0) != 0) {
      std::fprintf(stderr, "FAIL %s -> %s\n", line.c_str(), resp.c_str());
      failures->fetch_add(1);
    }
  };

  expect("REGISTER " + std::to_string(task) + " 1", "OK");
  expect("HEARTBEAT " + std::to_string(task) + " 1", "OK");
  expect("KVSET k" + std::to_string(task) + " v" + std::to_string(task),
         "OK");
  expect("KVGET k" + std::to_string(task), "OK v");
  if (task == 0) {
    // Chunk-scale value through the buffered read path.
    expect("KVSET big " + std::string(256 * 1024, 'x'), "OK");
    expect("KVGET big", "OK x");
  }
  for (int round = 0; round < kBarrierRounds; ++round) {
    // Reused named barrier across all tasks; nonce per call.
    expect("BARRIER smoke " + std::to_string(task) + " 20 " +
               std::to_string(100 * task + round + 1),
           "OK");
  }
  expect("STATPUT " + std::to_string(task) +
             " {\"step\":" + std::to_string(task) + "}",
         "OK");
  expect("STATDUMP 2", "OK");
  expect("HEALTH 0", "OK");
  expect("PROGRESS", "OK");
  expect("AGES", "OK");
  expect("TIME", "OK");
  expect("MEMBERS", "OK");
  expect("INFO", "OK num_tasks=");
  expect("SHARDINFO", "OK shard=");
  if (task == 2) {
    expect("RECONFIGURE", "OK");
  }
  expect("LEAVE " + std::to_string(task), "OK");
}

// Router-style session over a 2-instance sharded plane: control traffic
// (register/heartbeat/barrier/members) pinned to instance 0, KV traffic
// spread across both instances by a stable key hash — the same
// partitioning CoordinationRouter applies — with every handler on its
// own detached thread on BOTH servers concurrently.
void ShardedSession(int port0, int port1, int task,
                    std::atomic<int>* failures) {
  dtf::CoordClient control("127.0.0.1", port0, task);
  dtf::CoordClient kv1("127.0.0.1", port1, task);
  std::string resp;
  auto expect = [&](dtf::CoordClient& c, const std::string& line,
                    const char* prefix) {
    if (!c.Request(line, &resp, 5.0) || resp.rfind(prefix, 0) != 0) {
      std::fprintf(stderr, "FAIL(shard) %s -> %s\n", line.c_str(),
                   resp.c_str());
      failures->fetch_add(1);
    }
  };
  expect(control, "REGISTER " + std::to_string(task) + " 7", "OK");
  expect(control, "SHARDINFO", "OK shard=0 nshards=2");
  expect(kv1, "SHARDINFO", "OK shard=1 nshards=2");
  for (int i = 0; i < 8; ++i) {
    // Stable hash stand-in: even keys home on instance 0, odd on 1.
    dtf::CoordClient& home = (i % 2 == 0) ? control : kv1;
    std::string key = "sk" + std::to_string(task) + "_" +
                      std::to_string(i);
    expect(home, "KVSET " + key + " v" + std::to_string(i), "OK");
    expect(home, "KVGET " + key, "OK v");
  }
  expect(control, "HEARTBEAT " + std::to_string(task) + " 5", "OK");
  expect(control, "BARRIER sharded " + std::to_string(task) + " 20 " +
                      std::to_string(500 + task),
         "OK");
  expect(control, "MEMBERS", "OK");
  expect(control, "LEAVE " + std::to_string(task), "OK");
}

}  // namespace

int main() {
  // Heap-allocated exactly like the C ABI (dtf_coord_server_start) —
  // the production lifetime this smoke is certifying.
  auto* server = new dtf::CoordServer(0, kTasks,
                                      /*heartbeat_timeout=*/30.0);
  if (!server->ok()) {
    std::fprintf(stderr, "server failed to bind\n");
    return 1;
  }
  int port = server->port();
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  threads.reserve(kTasks);
  for (int task = 0; task < kTasks; ++task) {
    threads.emplace_back(ClientSession, port, task, &failures);
  }
  for (auto& t : threads) t.join();
  // Chaos drop/recover AFTER the concurrent sweep: the drop counter is
  // server-global, so exercising it concurrently would nondeterminism-
  // fail another task's request; here the only victim is our own probe.
  {
    dtf::CoordClient client("127.0.0.1", port, 0);
    std::string resp;
    if (!client.Request("CHAOS drop 1", &resp, 5.0) || resp != "OK") {
      std::fprintf(stderr, "FAIL chaos arm -> %s\n", resp.c_str());
      failures.fetch_add(1);
    }
    client.Request("KVGET k0", &resp, 1.0);  // dropped: failure expected
    if (!client.Request("CHAOS off", &resp, 5.0) || resp != "OK" ||
        !client.Request("KVGET k0", &resp, 5.0) ||
        resp.rfind("OK v0", 0) != 0) {
      std::fprintf(stderr, "FAIL chaos recover -> %s\n", resp.c_str());
      failures.fetch_add(1);
    }
  }
  // Sharded 2-instance session (ISSUE 13): a second server instance with
  // shard identity (1, 2), router-style client threads splitting control
  // and KV traffic across both, then Stop() racing a request wave on EACH
  // instance — the interleavings the sharded plane's mutex discipline
  // must survive.
  auto* shard0 = new dtf::CoordServer(0, kTasks, /*heartbeat_timeout=*/30.0);
  auto* shard1 = new dtf::CoordServer(0, kTasks, /*heartbeat_timeout=*/30.0);
  if (!shard0->ok() || !shard1->ok()) {
    std::fprintf(stderr, "sharded instances failed to bind\n");
    return 1;
  }
  shard0->SetShard(0, 2);
  shard1->SetShard(1, 2);
  {
    std::vector<std::thread> sharded;
    sharded.reserve(kTasks);
    for (int task = 0; task < kTasks; ++task) {
      sharded.emplace_back(ShardedSession, shard0->port(), shard1->port(),
                           task, &failures);
    }
    for (auto& t : sharded) t.join();
  }
  int p0 = shard0->port(), p1 = shard1->port();
  std::thread late0([p0] {
    dtf::CoordClient client("127.0.0.1", p0, 0);
    std::string resp;
    for (int i = 0; i < 20; ++i) client.Request("INFO", &resp, 0.2);
  });
  std::thread late1([p1] {
    dtf::CoordClient client("127.0.0.1", p1, 0);
    std::string resp;
    for (int i = 0; i < 20; ++i) client.Request("SHARDINFO", &resp, 0.2);
  });
  shard0->Stop();
  shard1->Stop();
  late0.join();
  late1.join();
  delete shard0;
  delete shard1;

  // One more wave racing Stop(): requests may fail (connection refused
  // mid-stop is fine) — only memory safety is under test here.
  std::thread late([port] {
    dtf::CoordClient client("127.0.0.1", port, 0);
    std::string resp;
    for (int i = 0; i < 20; ++i) client.Request("INFO", &resp, 0.2);
  });
  server->Stop();
  late.join();
  delete server;
  if (failures.load() != 0) {
    std::fprintf(stderr, "COORD_SMOKE_FAILED: %d protocol failure(s)\n",
                 failures.load());
    return 1;
  }
#if defined(__SANITIZE_THREAD__)
  const char* kMarker = "COORD_TSAN_SMOKE_OK";
#elif defined(__SANITIZE_ADDRESS__)
  const char* kMarker = "COORD_ASAN_SMOKE_OK";
#else
  const char* kMarker = "COORD_SMOKE_OK";
#endif
  std::printf("%s: %d tasks x %d barrier rounds, 17-command sweep, "
              "chaos drop/recover, 2-instance sharded session, "
              "racing stops\n",
              kMarker, kTasks, kBarrierRounds);
  return 0;
}
