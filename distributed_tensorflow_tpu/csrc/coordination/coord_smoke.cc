// Sanitizer smoke driver for the coordination service (ISSUE 10,
// docs/static_analysis.md "Sanitizer builds").
//
// Compiles coord.cc together with this main() under
// -fsanitize=thread,undefined (`make -C . tsan-smoke`) and runs a REAL
// coordination session in one process: a server on an ephemeral port,
// N client threads hammering the full protocol over real sockets —
// registration, heartbeats, reused barriers, KV (including a
// chunk-scale value), STATPUT/STATDUMP, MEMBERS/RECONFIGURE, TIME,
// HEALTH/PROGRESS/AGES/INFO, CHAOS drop/recover, LEAVE — then a
// concurrent Stop(); plus a coordinator-HA leg (HaSmoke below) driving
// journal streaming (REPLJOIN/REPLSTREAM), a late snapshot bootstrap, a
// forced promotion, and a client wave racing the failover.  Every
// handler runs on its own detached thread, so this exercises exactly
// the interleavings the mutex discipline in coord.cc must survive.
// ThreadSanitizer exits non-zero on any data race; the CI leg (ci.sh)
// fails on that exit status.
//
// Deliberately has no gtest/argparse dependencies: build and run.

#include "coord.cc"

#include <cassert>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kTasks = 4;
constexpr int kBarrierRounds = 3;

void ClientSession(int port, int task, std::atomic<int>* failures) {
  dtf::CoordClient client("127.0.0.1", port, task);
  std::string resp;
  auto expect = [&](const std::string& line, const char* prefix) {
    if (!client.Request(line, &resp, 5.0) ||
        resp.rfind(prefix, 0) != 0) {
      std::fprintf(stderr, "FAIL %s -> %s\n", line.c_str(), resp.c_str());
      failures->fetch_add(1);
    }
  };

  expect("REGISTER " + std::to_string(task) + " 1", "OK");
  expect("HEARTBEAT " + std::to_string(task) + " 1", "OK");
  expect("KVSET k" + std::to_string(task) + " v" + std::to_string(task),
         "OK");
  expect("KVGET k" + std::to_string(task), "OK v");
  if (task == 0) {
    // Chunk-scale value through the buffered read path.
    expect("KVSET big " + std::string(256 * 1024, 'x'), "OK");
    expect("KVGET big", "OK x");
  }
  for (int round = 0; round < kBarrierRounds; ++round) {
    // Reused named barrier across all tasks; nonce per call.
    expect("BARRIER smoke " + std::to_string(task) + " 20 " +
               std::to_string(100 * task + round + 1),
           "OK");
  }
  expect("STATPUT " + std::to_string(task) +
             " {\"step\":" + std::to_string(task) + "}",
         "OK");
  expect("STATDUMP 2", "OK");
  expect("HEALTH 0", "OK");
  expect("PROGRESS", "OK");
  expect("AGES", "OK");
  expect("TIME", "OK");
  expect("MEMBERS", "OK");
  expect("INFO", "OK num_tasks=");
  expect("SHARDINFO", "OK shard=");
  if (task == 2) {
    expect("RECONFIGURE", "OK");
  }
  expect("LEAVE " + std::to_string(task), "OK");
}

// Router-style session over a 2-instance sharded plane: control traffic
// (register/heartbeat/barrier/members) pinned to instance 0, KV traffic
// spread across both instances by a stable key hash — the same
// partitioning CoordinationRouter applies — with every handler on its
// own detached thread on BOTH servers concurrently.
void ShardedSession(int port0, int port1, int task,
                    std::atomic<int>* failures) {
  dtf::CoordClient control("127.0.0.1", port0, task);
  dtf::CoordClient kv1("127.0.0.1", port1, task);
  std::string resp;
  auto expect = [&](dtf::CoordClient& c, const std::string& line,
                    const char* prefix) {
    if (!c.Request(line, &resp, 5.0) || resp.rfind(prefix, 0) != 0) {
      std::fprintf(stderr, "FAIL(shard) %s -> %s\n", line.c_str(),
                   resp.c_str());
      failures->fetch_add(1);
    }
  };
  expect(control, "REGISTER " + std::to_string(task) + " 7", "OK");
  expect(control, "SHARDINFO", "OK shard=0 nshards=2");
  expect(kv1, "SHARDINFO", "OK shard=1 nshards=2");
  for (int i = 0; i < 8; ++i) {
    // Stable hash stand-in: even keys home on instance 0, odd on 1.
    dtf::CoordClient& home = (i % 2 == 0) ? control : kv1;
    std::string key = "sk" + std::to_string(task) + "_" +
                      std::to_string(i);
    expect(home, "KVSET " + key + " v" + std::to_string(i), "OK");
    expect(home, "KVGET " + key, "OK v");
  }
  expect(control, "HEARTBEAT " + std::to_string(task) + " 5", "OK");
  expect(control, "BARRIER sharded " + std::to_string(task) + " 20 " +
                      std::to_string(500 + task),
         "OK");
  expect(control, "MEMBERS", "OK");
  expect(control, "LEAVE " + std::to_string(task), "OK");
}

std::string Body(const std::string& resp) {
  // Strip the generation/role reply trailer (exact-match checks below).
  auto cut = resp.rfind('\x1f');
  return cut == std::string::npos ? resp : resp.substr(0, cut);
}

// Poll an INFO field ("repl_applied=", "role=", ...) until it reaches
// `want` (string prefix match on the value) or ~10s pass.
bool WaitInfoField(int port, const std::string& field,
                   const std::string& want) {
  dtf::CoordClient client("127.0.0.1", port, -1);
  for (int i = 0; i < 500; ++i) {
    std::string resp;
    if (client.Request("INFO", &resp, 2.0)) {
      auto at = resp.find(" " + field + "=");
      if (at != std::string::npos) {
        auto val = resp.substr(at + field.size() + 2);
        if (val.rfind(want, 0) == 0 ||
            val.rfind(want + " ", 0) == 0) {
          return true;
        }
        // Numeric >=: parse both when want is a number.
        char* end = nullptr;
        long have = std::strtol(val.c_str(), &end, 10);
        if (end != val.c_str()) {
          long target = std::strtol(want.c_str(), nullptr, 10);
          if (have >= target) return true;
        }
      }
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  return false;
}

// Coordinator-HA leg (ISSUE 15): a REAL primary+standby pair streaming
// the journal, a late-joining second standby (snapshot bootstrap), a
// forced promotion (primary Stop() + 0.5s lease), and a client request
// wave racing the failover — the interleavings the replication thread's
// mutex discipline must survive under both sanitizers.
int HaSmoke(std::atomic<int>* failures) {
  auto* primary = new dtf::CoordServer(0, kTasks, /*heartbeat_timeout=*/30.0);
  if (!primary->ok()) {
    std::fprintf(stderr, "ha primary failed to bind\n");
    return 1;
  }
  std::string paddr = "127.0.0.1:" + std::to_string(primary->port());
  auto* standby = new dtf::CoordServer(0, kTasks, 30.0, "", 0, 1, paddr,
                                       /*lease_timeout=*/0.5);
  if (!standby->ok()) {
    std::fprintf(stderr, "ha standby failed to bind\n");
    return 1;
  }
  int pport = primary->port(), sport = standby->port();
  // Real traffic on the primary: registrations, KV, a barrier round.
  {
    std::vector<std::thread> threads;
    for (int task = 0; task < kTasks; ++task) {
      threads.emplace_back([pport, task, failures] {
        dtf::CoordClient client("127.0.0.1", pport, task);
        std::string resp;
        auto expect = [&](const std::string& line, const char* prefix) {
          if (!client.Request(line, &resp, 5.0) ||
              resp.rfind(prefix, 0) != 0) {
            std::fprintf(stderr, "FAIL(ha) %s -> %s\n", line.c_str(),
                         resp.c_str());
            failures->fetch_add(1);
          }
        };
        expect("REGISTER " + std::to_string(task) + " 9", "OK");
        expect("KVSET ha" + std::to_string(task) + " v" +
                   std::to_string(task),
               "OK");
        expect("BARRIER ha " + std::to_string(task) + " 20 " +
                   std::to_string(900 + task),
               "OK");
      });
    }
    for (auto& t : threads) t.join();
  }
  // A second standby joins LATE: its whole state arrives as the
  // REPLJOIN snapshot, racing the first standby's incremental stream.
  auto* late_standby = new dtf::CoordServer(0, kTasks, 30.0, "", 0, 1,
                                            paddr, 0.5);
  if (!late_standby->ok()) {
    std::fprintf(stderr, "ha late standby failed to bind\n");
    return 1;
  }
  std::string head = std::to_string(kTasks * 3 + 1);  // >= traffic above
  if (!WaitInfoField(sport, "repl_applied", "9") ||
      !WaitInfoField(late_standby->port(), "repl_applied", "9")) {
    std::fprintf(stderr, "FAIL(ha) standbys never caught up\n");
    failures->fetch_add(1);
  }
  (void)head;
  // Retire the late standby BEFORE the kill so exactly one candidate
  // promotes (the most-caught-up rule is a tie otherwise).
  late_standby->Stop();
  delete late_standby;
  // Request wave against the standby racing the primary's death and the
  // promotion: NOTPRIMARY refusals flipping to OKs mid-wave is the
  // expected shape; only memory safety and the final state are asserted.
  std::thread wave([sport] {
    dtf::CoordClient client("127.0.0.1", sport, 0);
    std::string resp;
    for (int i = 0; i < 100; ++i) {
      client.Request("KVGET ha0", &resp, 0.5);
      client.Request("INFO", &resp, 0.5);
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  });
  primary->Stop();
  bool promoted = WaitInfoField(sport, "role", "primary");
  wave.join();
  if (!promoted) {
    std::fprintf(stderr, "FAIL(ha) standby never promoted\n");
    failures->fetch_add(1);
  } else {
    dtf::CoordClient client("127.0.0.1", sport, 0);
    std::string resp;
    // Replicated state survived the failover...
    if (!client.Request("KVGET ha0", &resp, 5.0) ||
        Body(resp) != "OK v0") {
      std::fprintf(stderr, "FAIL(ha) post-promotion KVGET -> %s\n",
                   resp.c_str());
      failures->fetch_add(1);
    }
    // ...including the barrier's done-nonces: re-presenting an already-
    // released arrival is re-answered OK instantly, never re-armed (the
    // never-double-release rule across promotion).
    if (!client.Request("BARRIER ha 0 0.5 900", &resp, 5.0) ||
        Body(resp) != "OK") {
      std::fprintf(stderr, "FAIL(ha) replayed nonce -> %s\n",
                   resp.c_str());
      failures->fetch_add(1);
    }
    // The promoted standby accepts mutations at generation 2.
    if (!client.Request("KVSET post promo", &resp, 5.0) ||
        Body(resp) != "OK" ||
        resp.find("gen=2 role=primary") == std::string::npos) {
      std::fprintf(stderr, "FAIL(ha) post-promotion KVSET -> %s\n",
                   resp.c_str());
      failures->fetch_add(1);
    }
  }
  standby->Stop();
  delete standby;
  delete primary;
  return 0;
}

// KV-shard HA leg (ISSUE 18): the SAME journal-streaming machinery on a
// non-control instance — a shard-1-of-2 primary + warm standby, chunked
// KV families published chunks-before-meta, concurrent writer threads,
// then a forced promotion with a request wave racing it.  The promoted
// standby must answer with its shard identity intact, at generation 2,
// and must never serve a meta record whose chunks are missing (the
// torn-blob invariant relies on in-order journal application).
int KvShardHaSmoke(std::atomic<int>* failures) {
  auto* primary = new dtf::CoordServer(0, kTasks, /*heartbeat_timeout=*/30.0,
                                       "", /*shard=*/1, /*nshards=*/2);
  if (!primary->ok()) {
    std::fprintf(stderr, "kvha primary failed to bind\n");
    return 1;
  }
  std::string paddr = "127.0.0.1:" + std::to_string(primary->port());
  auto* standby = new dtf::CoordServer(0, kTasks, 30.0, "", 1, 2, paddr,
                                       /*lease_timeout=*/0.5);
  if (!standby->ok()) {
    std::fprintf(stderr, "kvha standby failed to bind\n");
    return 1;
  }
  int pport = primary->port(), sport = standby->port();
  // Concurrent writers publishing chunked families: per task, chunks
  // FIRST, the meta record LAST — exactly the blob-publish ordering the
  // replication stream must preserve.
  {
    std::vector<std::thread> threads;
    for (int task = 0; task < kTasks; ++task) {
      threads.emplace_back([pport, task, failures] {
        dtf::CoordClient client("127.0.0.1", pport, task);
        std::string resp;
        auto expect = [&](const std::string& line, const char* prefix) {
          if (!client.Request(line, &resp, 5.0) ||
              resp.rfind(prefix, 0) != 0) {
            std::fprintf(stderr, "FAIL(kvha) %s -> %s\n", line.c_str(),
                         resp.c_str());
            failures->fetch_add(1);
          }
        };
        std::string base = "kb" + std::to_string(task);
        expect("KVSET " + base + ".c0 " + std::string(64 * 1024, 'a'),
               "OK");
        expect("KVSET " + base + ".c1 " + std::string(64 * 1024, 'b'),
               "OK");
        expect("KVSET " + base + ".v 2:meta" + std::to_string(task),
               "OK");
      });
    }
    for (auto& t : threads) t.join();
  }
  if (!WaitInfoField(sport, "repl_applied", std::to_string(kTasks * 3))) {
    std::fprintf(stderr, "FAIL(kvha) standby never caught up\n");
    failures->fetch_add(1);
  }
  // Readers racing the primary's death and the promotion: refusals
  // flipping to OKs mid-wave is the expected shape.
  std::thread wave([sport] {
    dtf::CoordClient client("127.0.0.1", sport, 0);
    std::string resp;
    for (int i = 0; i < 100; ++i) {
      client.Request("KVGET kb0.v", &resp, 0.5);
      client.Request("SHARDINFO", &resp, 0.5);
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  });
  primary->Stop();
  bool promoted = WaitInfoField(sport, "role", "primary");
  wave.join();
  if (!promoted) {
    std::fprintf(stderr, "FAIL(kvha) standby never promoted\n");
    failures->fetch_add(1);
  } else {
    dtf::CoordClient client("127.0.0.1", sport, 0);
    std::string resp;
    // Shard identity survived the promotion.
    if (!client.Request("SHARDINFO", &resp, 5.0) ||
        resp.rfind("OK shard=1 nshards=2", 0) != 0) {
      std::fprintf(stderr, "FAIL(kvha) post-promotion SHARDINFO -> %s\n",
                   resp.c_str());
      failures->fetch_add(1);
    }
    // Chunk-before-meta held: every meta record on the promoted standby
    // has its chunks readable (the stream applied in sequence order).
    for (int task = 0; task < kTasks; ++task) {
      std::string base = "kb" + std::to_string(task);
      if (!client.Request("KVGET " + base + ".v", &resp, 5.0) ||
          Body(resp) != "OK 2:meta" + std::to_string(task)) {
        std::fprintf(stderr, "FAIL(kvha) meta %s -> %s\n", base.c_str(),
                     resp.c_str());
        failures->fetch_add(1);
        continue;
      }
      for (const char* c : {".c0", ".c1"}) {
        if (!client.Request("KVGET " + base + c, &resp, 5.0) ||
            resp.rfind("OK ", 0) != 0 || resp.size() < 64 * 1024) {
          std::fprintf(stderr, "FAIL(kvha) torn blob: %s%s -> %.40s\n",
                       base.c_str(), c, resp.c_str());
          failures->fetch_add(1);
        }
      }
    }
    // Mutations accepted at generation 2, shard identity in the trailer.
    if (!client.Request("KVSET kvpost promo", &resp, 5.0) ||
        Body(resp) != "OK" ||
        resp.find("gen=2 role=primary") == std::string::npos) {
      std::fprintf(stderr, "FAIL(kvha) post-promotion KVSET -> %s\n",
                   resp.c_str());
      failures->fetch_add(1);
    }
  }
  standby->Stop();
  delete standby;
  delete primary;
  return 0;
}

}  // namespace

int main() {
  // Heap-allocated exactly like the C ABI (dtf_coord_server_start) —
  // the production lifetime this smoke is certifying.
  auto* server = new dtf::CoordServer(0, kTasks,
                                      /*heartbeat_timeout=*/30.0);
  if (!server->ok()) {
    std::fprintf(stderr, "server failed to bind\n");
    return 1;
  }
  int port = server->port();
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  threads.reserve(kTasks);
  for (int task = 0; task < kTasks; ++task) {
    threads.emplace_back(ClientSession, port, task, &failures);
  }
  for (auto& t : threads) t.join();
  // Chaos drop/recover AFTER the concurrent sweep: the drop counter is
  // server-global, so exercising it concurrently would nondeterminism-
  // fail another task's request; here the only victim is our own probe.
  {
    dtf::CoordClient client("127.0.0.1", port, 0);
    std::string resp;
    if (!client.Request("CHAOS drop 1", &resp, 5.0) || Body(resp) != "OK") {
      std::fprintf(stderr, "FAIL chaos arm -> %s\n", resp.c_str());
      failures.fetch_add(1);
    }
    client.Request("KVGET k0", &resp, 1.0);  // dropped: failure expected
    if (!client.Request("CHAOS off", &resp, 5.0) || Body(resp) != "OK" ||
        !client.Request("KVGET k0", &resp, 5.0) ||
        resp.rfind("OK v0", 0) != 0) {
      std::fprintf(stderr, "FAIL chaos recover -> %s\n", resp.c_str());
      failures.fetch_add(1);
    }
  }
  // Sharded 2-instance session (ISSUE 13): a second server instance with
  // shard identity (1, 2), router-style client threads splitting control
  // and KV traffic across both, then Stop() racing a request wave on EACH
  // instance — the interleavings the sharded plane's mutex discipline
  // must survive.
  auto* shard0 = new dtf::CoordServer(0, kTasks, /*heartbeat_timeout=*/30.0);
  auto* shard1 = new dtf::CoordServer(0, kTasks, /*heartbeat_timeout=*/30.0);
  if (!shard0->ok() || !shard1->ok()) {
    std::fprintf(stderr, "sharded instances failed to bind\n");
    return 1;
  }
  shard0->SetShard(0, 2);
  shard1->SetShard(1, 2);
  {
    std::vector<std::thread> sharded;
    sharded.reserve(kTasks);
    for (int task = 0; task < kTasks; ++task) {
      sharded.emplace_back(ShardedSession, shard0->port(), shard1->port(),
                           task, &failures);
    }
    for (auto& t : sharded) t.join();
  }
  int p0 = shard0->port(), p1 = shard1->port();
  std::thread late0([p0] {
    dtf::CoordClient client("127.0.0.1", p0, 0);
    std::string resp;
    for (int i = 0; i < 20; ++i) client.Request("INFO", &resp, 0.2);
  });
  std::thread late1([p1] {
    dtf::CoordClient client("127.0.0.1", p1, 0);
    std::string resp;
    for (int i = 0; i < 20; ++i) client.Request("SHARDINFO", &resp, 0.2);
  });
  shard0->Stop();
  shard1->Stop();
  late0.join();
  late1.join();
  delete shard0;
  delete shard1;

  // One more wave racing Stop(): requests may fail (connection refused
  // mid-stop is fine) — only memory safety is under test here.
  std::thread late([port] {
    dtf::CoordClient client("127.0.0.1", port, 0);
    std::string resp;
    for (int i = 0; i < 20; ++i) client.Request("INFO", &resp, 0.2);
  });
  server->Stop();
  late.join();
  delete server;
  // Coordinator-HA leg: primary+standby journal streaming, snapshot
  // bootstrap, forced promotion, request wave racing the failover.
  if (HaSmoke(&failures) != 0) return 1;
  // KV-shard HA leg: the same promotion machinery on a shard-1-of-2
  // instance, chunked families published chunks-before-meta.
  if (KvShardHaSmoke(&failures) != 0) return 1;
  if (failures.load() != 0) {
    std::fprintf(stderr, "COORD_SMOKE_FAILED: %d protocol failure(s)\n",
                 failures.load());
    return 1;
  }
#if defined(__SANITIZE_THREAD__)
  const char* kMarker = "COORD_TSAN_SMOKE_OK";
#elif defined(__SANITIZE_ADDRESS__)
  const char* kMarker = "COORD_ASAN_SMOKE_OK";
#else
  const char* kMarker = "COORD_SMOKE_OK";
#endif
  std::printf("%s: %d tasks x %d barrier rounds, 19-command sweep, "
              "chaos drop/recover, 2-instance sharded session, "
              "primary+standby failover, KV-shard failover, "
              "racing stops\n",
              kMarker, kTasks, kBarrierRounds);
  return 0;
}
