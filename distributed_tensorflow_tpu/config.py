"""Flag/config system (reference parity: C1/C2).

The reference declares 11 flags via ``tf.app.flags`` (reference
``distributed.py:8-34``) and validates ``job_name``/``task_index`` in ``main``
(``distributed.py:40-47``).  This module provides the same surface —
``flags.DEFINE_*`` + a module-level ``FLAGS`` object + ``app.run(main)`` —
without TensorFlow, and with TPU-shaped defaults (no CUDA env vars; one
process per TPU-VM host).

Unlike ``tf.app.flags`` this registry is instantiable, so tests can build
isolated flag sets.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Callable, Sequence


class FlagValues:
    """Holds flag definitions and parsed values (attribute access like TF's FLAGS)."""

    def __init__(self) -> None:
        object.__setattr__(self, "_defs", {})  # name -> (type_fn, default, help)
        object.__setattr__(self, "_values", {})
        object.__setattr__(self, "_parsed", False)

    def _define(self, name: str, default: Any, help_str: str, type_fn: Callable) -> None:
        self._defs[name] = (type_fn, default, help_str)
        self._values[name] = default

    def __getattr__(self, name: str) -> Any:
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(f"Unknown flag: {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        if name not in self._defs:
            raise AttributeError(f"Cannot set undefined flag {name!r}")
        self._values[name] = value

    def parse(self, argv: Sequence[str] | None = None) -> list[str]:
        """Parse argv (defaults to ``sys.argv[1:]``); returns leftover positional args."""
        if argv is None:
            argv = sys.argv[1:]
        parser = argparse.ArgumentParser(add_help=True, allow_abbrev=False)
        for name, (type_fn, default, help_str) in self._defs.items():
            if type_fn is bool:
                parser.add_argument(
                    f"--{name}", default=default, help=help_str,
                    type=_parse_bool, nargs="?", const=True)
            else:
                parser.add_argument(f"--{name}", default=default, help=help_str,
                                    type=type_fn)
        ns, leftover = parser.parse_known_args(list(argv))
        for name in self._defs:
            self._values[name] = getattr(ns, name)
        object.__setattr__(self, "_parsed", True)
        return leftover

    def as_dict(self) -> dict:
        return dict(self._values)


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool) or v is None:
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "t", "1", "yes", "y"):
        return True
    if s in ("false", "f", "0", "no", "n", ""):
        return False
    raise argparse.ArgumentTypeError(f"Not a boolean: {v!r}")


class _FlagsModule:
    """Mirrors the ``tf.app.flags`` API: DEFINE_* + FLAGS."""

    def __init__(self, flag_values: FlagValues | None = None) -> None:
        self.FLAGS = flag_values or FlagValues()

    def DEFINE_string(self, name: str, default: str | None, help_str: str) -> None:
        self.FLAGS._define(name, default, help_str, str)

    def DEFINE_integer(self, name: str, default: int | None, help_str: str) -> None:
        self.FLAGS._define(name, default, help_str, int)

    def DEFINE_float(self, name: str, default: float | None, help_str: str) -> None:
        self.FLAGS._define(name, default, help_str, float)

    def DEFINE_boolean(self, name: str, default: bool | None, help_str: str) -> None:
        self.FLAGS._define(name, default, help_str, bool)

    DEFINE_bool = DEFINE_boolean


# Module-level singleton, like tf.app.flags.
flags = _FlagsModule()
FLAGS = flags.FLAGS


def define_training_flags(f: _FlagsModule | None = None) -> FlagValues:
    """Declare the reference's 11 flags (``distributed.py:8-34``) with TPU defaults.

    ``ps_hosts``/``worker_hosts`` are kept for CLI compatibility but reinterpreted:
    ``worker_hosts`` lists the TPU-VM hosts (one process each) and ``ps_hosts[0]``
    doubles as the coordination-service address (there is no parameter server —
    parameters live sharded in TPU HBM).
    """
    f = f or flags
    f.DEFINE_string("data_dir", "/tmp/mnist-data", "Directory for storing mnist data")
    f.DEFINE_integer("hidden_units", 100, "Number of units in the hidden layer of the NN")
    f.DEFINE_integer("train_steps", 100000, "Number of training steps to perform")
    f.DEFINE_integer("batch_size", 100, "Training batch size (global)")
    f.DEFINE_float("learning_rate", 0.01, "Learning rate")
    f.DEFINE_string("ps_hosts", "localhost:2222",
                    "Coordination-service address (hostname:port). Kept for CLI parity "
                    "with the reference's parameter-server flag; no PS process exists.")
    f.DEFINE_string("worker_hosts", "localhost:2223",
                    "Comma-separated list of hostname:port pairs, one per TPU-VM host")
    f.DEFINE_string("job_name", None, "job name: worker or ps")
    f.DEFINE_integer("task_index", None, "Index of task within the job")
    f.DEFINE_boolean("sync_replicas", False,
                     "Use the sync_replicas (synchronized replicas) mode, wherein the "
                     "parameter updates from workers are aggregated (AllReduce over ICI) "
                     "before being applied, avoiding stale gradients")
    f.DEFINE_integer("replicas_to_aggregate", None,
                     "Number of replicas to aggregate before the parameter update is "
                     "applied (sync_replicas mode only; default: num_workers). "
                     "TPU-native semantics: R < num_workers enables masked "
                     "aggregation over the LIVE worker set (dead workers drop "
                     "on --heartbeat_timeout; slow ones on --straggler_lag), "
                     "renormalized each step — not literally 'first R of N' "
                     "(AllReduce has no first-R notion; see PARITY.md N3)")
    return f.FLAGS


def validate_role_flags(FLAGS: FlagValues) -> None:
    """Reference parity: hard error on missing job_name/task_index (``distributed.py:40-47``)."""
    if FLAGS.job_name is None or FLAGS.job_name == "":
        raise ValueError("Must specify an explicit job_name !")
    print(f"job_name : {FLAGS.job_name}")
    if FLAGS.task_index is None or FLAGS.task_index == "":
        raise ValueError("Must specify an explicit task_index!")
    print(f"task_index : {FLAGS.task_index}")


class app:
    """``tf.app.run`` equivalent: parse flags then call main(leftover_argv)."""

    @staticmethod
    def run(main: Callable, argv: Sequence[str] | None = None) -> Any:
        leftover = FLAGS.parse(argv)
        # Surface probable typos: unknown --flags are passed through to main
        # (tf.app.run leftover semantics) but never parsed by anyone.
        for arg in leftover:
            if arg.startswith("--"):
                print(f"WARNING: unrecognized flag {arg!r} ignored",
                      file=sys.stderr)
        return main(leftover)
