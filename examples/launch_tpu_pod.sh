#!/usr/bin/env bash
# TPU pod-slice launch template — one trainer process per TPU-VM host
# (the reference's one-process-per-node shape, README.md:7-15, without CUDA
# env vars; device visibility comes from the TPU runtime, not the launcher).
#
# Run THIS SCRIPT ON EVERY HOST of the slice, e.g. via
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
#     --command="WORKER_HOSTS=... TASK_INDEX=\$(hostname | sed 's/.*-//') \
#                bash launch_tpu_pod.sh"
#
# Required env:
#   WORKER_HOSTS  comma-separated host:port list, one entry per TPU-VM host
#   TASK_INDEX    this host's index into WORKER_HOSTS (chief = 0)
# Optional env:
#   COORD_HOST    coordination-service address (default: first worker host);
#                 host 0 serves it in-process — no separate PS machine exists
#   MODEL         mnist_mlp | lenet5 | resnet20 | bert_tiny | bert_moe | gpt_mini
set -euo pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

: "${WORKER_HOSTS:?set WORKER_HOSTS (host:port per TPU-VM host)}"
: "${TASK_INDEX:?set TASK_INDEX (this host's index; chief = 0)}"
COORD_HOST=${COORD_HOST:-${WORKER_HOSTS%%,*}}
MODEL=${MODEL:-mnist_mlp}
LOGDIR=${LOGDIR:-/tmp/dtf_tpu_pod_run}

# Multi-axis parallelism knobs (sized for the whole slice, not one host):
#   --tensor_parallel N    'model' mesh axis (Megatron-style TP)
#   --sequence_parallel N  'seq' axis + --attention_backend=ring
#   --expert_parallel N    'expert' axis with --model=bert_moe
# The data axis is inferred from the remaining chips.
exec python -m distributed_tensorflow_tpu.train \
  --job_name=worker --task_index="${TASK_INDEX}" \
  --ps_hosts="${COORD_HOST}" --worker_hosts="${WORKER_HOSTS}" \
  --model="${MODEL}" --sync_replicas=true \
  --train_steps=100000 --batch_size=100 --learning_rate=0.01 \
  --steps_per_call=10 --log_every=100 --logdir="${LOGDIR}" \
  --metrics_file="${LOGDIR}/metrics.jsonl" \
  "$@"
