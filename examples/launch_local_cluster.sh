#!/usr/bin/env bash
# Local multi-process cluster — the TPU-native analog of the reference's
# launch recipe (reference README.md:7-15: 1 PS + workers on localhost with
# CUDA_VISIBLE_DEVICES pinning; here: 1 coordination-service process + 2
# worker processes on a virtual CPU mesh, no GPU env vars).
#
# Usage: examples/launch_local_cluster.sh [extra trainer flags...]
set -euo pipefail
cd "$(dirname "$0")/.."

export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export DTF_TPU_DISABLE_JAX_DISTRIBUTED=1  # control-plane demo on one machine

PS_PORT=${PS_PORT:-2222}
W0_PORT=${W0_PORT:-2223}
W1_PORT=${W1_PORT:-2224}
LOGDIR=${LOGDIR:-/tmp/dtf_tpu_local_cluster}

COMMON=(
  --platform=cpu
  --ps_hosts="localhost:${PS_PORT}"
  --worker_hosts="localhost:${W0_PORT},localhost:${W1_PORT}"
  --data_dir=/tmp/mnist-data
  --train_steps=200 --batch_size=100 --learning_rate=0.01
  --sync_replicas=true --log_every=10 --logdir="${LOGDIR}"
  "$@"
)

python -m distributed_tensorflow_tpu.train --job_name=ps --task_index=0 \
  "${COMMON[@]}" &
PS_PID=$!
trap 'kill ${PS_PID} 2>/dev/null || true' EXIT

python -m distributed_tensorflow_tpu.train --job_name=worker --task_index=1 \
  "${COMMON[@]}" &
W1_PID=$!

python -m distributed_tensorflow_tpu.train --job_name=worker --task_index=0 \
  "${COMMON[@]}"

wait ${W1_PID}
echo "local cluster run complete; checkpoints in ${LOGDIR}"
