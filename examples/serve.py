"""Minimal serving shim for exported StableHLO artifacts — closes the
train → export → serve loop (the reference never had one: its graph dies
with the process, reference ``distributed.py:108-131``).

Loads an artifact written by ``tools/export_model.py`` (self-contained:
weights are baked-in constants; symbolic batch dimension) and answers HTTP
requests, micro-batching concurrent callers into one device call::

    python -m distributed_tensorflow_tpu.tools.export_model \
        --model=gpt_mini --logdir <run>/gpt_mini --output /tmp/g.stablehlo
    python examples/serve.py --artifact /tmp/g.stablehlo --port 8600

    curl -d '{"prompt": [10, 11, 12], "num_tokens": 8}' \
        localhost:8600/generate           # gpt_mini: greedy decode
    curl -d '{"prompt": [10, 11, 12], "num_tokens": 8,
              "temperature": 0.8, "top_k": 40, "top_p": 0.9, "seed": 1}' \
        localhost:8600/generate           # sampled (r5): per-request
                                          # config, reproducible per seed
    curl -d '{"inputs": [[...784 floats...]]}' \
        localhost:8600/predict            # classifiers: raw forward
    curl localhost:8600/healthz

Decode prefers the artifact's KV-CACHED pair when the export wrote one
(``<artifact>.prefill`` + ``<artifact>.decode``, see
``tools/export_model.py::export_gpt_decode``): the prompt prefills
per-layer caches in one pass, then each device call generates a CHUNK of
tokens entirely on device against the caches — O(seq_len) per token
(O(window) for sliding-window checkpoints, whose pair carries a RING
cache and a per-row lengths input to prefill), with dispatch cost
amortized over the chunk.  Without the pair (older artifacts) decode
falls back to running the exported fixed-length FORWARD iteratively
(argmax feed-back at each row's own frontier) — O(S²) per token, the
fully-self-contained trade-off.
``eos_id`` stops a row early; rows in one micro-batch step together until
every row is done.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

# `python examples/serve.py` runs with examples/ as sys.path[0]; make the
# repo checkout importable too (a pip-installed package needs no help).
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.append(_REPO)


def load_artifact(path: str):
    """(callable, metadata, cached) from an export + its .json sidecar.

    ``cached`` is None, or — when the sidecar's ``decode`` section points
    at prefill/decode blobs that exist next to the artifact — a dict with
    jitted ``prefill``/``decode`` callables plus the cache geometry.  The
    jit wrapper is what caches one compilation per (batch, prompt-bucket)
    shape across requests."""
    from distributed_tensorflow_tpu.tools.export_model import load_exported

    exported = load_exported(path)
    with open(path + ".json") as fh:
        meta = json.load(fh)
    cached = None
    dmeta = meta.get("decode")
    if dmeta:
        base = os.path.dirname(os.path.abspath(path))
        pre_path = os.path.join(base, dmeta["files"]["prefill"])
        dec_path = os.path.join(base, dmeta["files"]["decode"])
        if os.path.exists(pre_path) and os.path.exists(dec_path):
            import jax
            cached = {
                "prefill": jax.jit(load_exported(pre_path).call),
                "decode": jax.jit(load_exported(dec_path).call),
                "capacity": int(dmeta["capacity"]),
                "chunk": int(dmeta["chunk"]),
                # Windowed (ring-cache) pairs take a per-row lengths input
                # to prefill (older sidecars lack the key -> full cache).
                "window": int(dmeta.get("window", 0)),
            }
            samp_name = dmeta["files"].get("decode_sample")
            samp_path = (os.path.join(base, samp_name) if samp_name
                         else None)
            if samp_path and os.path.exists(samp_path):
                # Sampled decode (r5): temperature/top-k/top-p as per-row
                # traced inputs — absent on pre-r5 artifacts (greedy only).
                cached["decode_sample"] = jax.jit(
                    load_exported(samp_path).call)
    return exported, meta, cached


def decode_batch(call, prompts: list[list[int]], num_tokens: list[int],
                 seq_len: int, eos_id: int | None = None) -> list[list[int]]:
    """Greedy decode a micro-batch through the exported forward.

    All rows step together (one device call per token across the whole
    batch); each row stops contributing once its own budget — or its eos —
    is reached.  Returns prompt + generation per row.
    """
    B = len(prompts)
    lens = np.asarray([len(p) for p in prompts])
    want = np.asarray(num_tokens)
    if np.any(lens + want > seq_len):
        raise ValueError(f"prompt + num_tokens exceeds the artifact's "
                         f"seq_len={seq_len}")
    if np.any(lens < 1) or np.any(want < 1):
        raise ValueError("empty prompt or non-positive num_tokens")
    toks = np.zeros((B, seq_len), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    done = np.zeros(B, bool)
    rows = np.arange(B)
    for step in range(int(want.max())):
        logits = call(toks)                        # [B, S, V] on device
        # Each row's predictor position; rows whose budget is spent keep
        # stepping with the rest of the batch, so clamp their (discarded)
        # reads inside the sequence.  Index on DEVICE first: only the
        # [B, V] frontier rows cross the host-transfer boundary, not the
        # whole [B, S, V] tensor.
        frontier = np.minimum(lens + step - 1, seq_len - 1)
        nxt = np.argmax(np.asarray(logits[rows, frontier]), axis=-1)
        exhausted = step >= want
        if eos_id is not None:
            nxt = np.where(done, eos_id, nxt)
        keep = ~exhausted
        toks[np.arange(B)[keep], (lens + step)[keep]] = nxt[keep].astype(
            np.int32)
        if eos_id is not None:
            done |= nxt == eos_id
        if np.all(exhausted | (done if eos_id is not None else False)):
            break
    out = []
    for i in range(B):
        row = toks[i, :lens[i] + want[i]].tolist()
        if eos_id is not None and eos_id in row[lens[i]:]:
            row = row[:lens[i] + row[lens[i]:].index(eos_id) + 1]
        out.append(row)
    return out


def decode_batch_cached(cached: dict, prompts: list[list[int]],
                        num_tokens: list[int], eos_id: int | None = None,
                        pad_batch: int | None = None,
                        sampling: dict | None = None) -> list[list[int]]:
    """Greedy decode a micro-batch through the KV-cached exported pair.

    One ``prefill`` call fills the caches from the right-padded prompts,
    then each ``decode`` call generates ``chunk`` tokens per row entirely
    on device (per-row ragged frontiers; junk K/V in a row's pad slots is
    masked/overwritten before it can be attended — see
    ``export_gpt_decode``).  ``pad_batch`` pads the batch with dummy rows
    and prompt lengths to 64-multiples so the jit cache sees a bounded
    shape set instead of compiling per request mix.  Rows that finish
    early keep stepping with the batch; their extra tokens are trimmed
    host-side, and cache writes past capacity are dropped by XLA's
    scatter OOB rule (those rows' outputs are already discarded).
    Returns prompt + generation per row.

    ``sampling`` (r5): ``{"temperature": [..], "top_k": [..],
    "top_p": [..], "seed": int}`` with one entry per row — routed through
    the artifact's sampled-decode blob (per-row traced inputs, so mixed
    configs share one micro-batch; rows with temperature 0 decode
    greedily).  Requires an artifact exported with the ``decode_sample``
    blob.
    """
    capacity, chunk = cached["capacity"], cached["chunk"]
    B = len(prompts)
    lens = np.asarray([len(p) for p in prompts])
    want = np.asarray(num_tokens)
    if np.any(lens + want > capacity):
        raise ValueError(f"prompt + num_tokens exceeds the artifact's "
                         f"seq_len={capacity}")
    if np.any(lens < 1) or np.any(want < 1):
        raise ValueError("empty prompt or non-positive num_tokens")
    Bp = max(B, pad_batch or 0)
    Ppad = min(capacity, ((int(lens.max()) + 63) // 64) * 64)
    toks = np.zeros((Bp, Ppad), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    if cached.get("window"):
        # Ring-cache pair: prefill needs each row's true length so pad
        # K/V never enters the ring (batch-pad dummy rows count as
        # length-1 prompts of token 0 — consistent with their frontier
        # below).
        lengths = np.ones((Bp,), np.int32)
        lengths[:B] = lens
        caches = cached["prefill"](toks, lengths)
    else:
        caches = cached["prefill"](toks)
    frontier = np.zeros((Bp,), np.int32)
    positions = np.zeros((Bp,), np.int32)
    for i, p in enumerate(prompts):
        frontier[i] = p[-1]
        positions[i] = len(p) - 1
    eos = np.int32(-1 if eos_id is None else eos_id)
    tok_dev, pos_dev = frontier, positions
    done = np.zeros((Bp,), bool)  # rows that emitted eos in a prior call
    if sampling is not None:
        if "decode_sample" not in cached:
            raise ValueError("artifact has no sampled-decode blob; "
                             "re-export or use greedy decode")
        temp = np.zeros((Bp,), np.float32)
        tk = np.zeros((Bp,), np.int32)
        tp = np.zeros((Bp,), np.float32)
        temp[:B] = sampling["temperature"]
        tk[:B] = sampling["top_k"]
        tp[:B] = sampling["top_p"]
        seed = np.int32(sampling.get("seed", 0))

        def decode_call(tok, pos, eos, done, caches):
            return cached["decode_sample"](tok, pos, eos, done, caches,
                                           seed, temp, tk, tp)
    else:
        decode_call = cached["decode"]
    outs: list = []
    produced = 0
    for _ in range(-(-int(want.max()) // chunk)):
        out, caches = decode_call(tok_dev, pos_dev, eos, done, caches)
        produced += chunk
        tok_dev, pos_dev = out[:, -1], pos_dev + chunk
        if eos_id is None:
            # No early-exit condition to check: keep the chunks on device
            # and fetch ONCE below — a host sync per chunk would serialize
            # the decode on the host/link round trip.
            outs.append(out)
            continue
        out_np = np.asarray(out)
        outs.append(out_np[:B])
        done[:B] |= (out_np[:B] == eos_id).any(axis=1)
        if all(done[i] or produced >= want[i] for i in range(B)):
            break
    gen = np.concatenate([np.asarray(o)[:B] for o in outs], axis=1)
    out_rows = []
    for i in range(B):
        row = list(prompts[i]) + gen[i, :want[i]].tolist()
        tail = row[lens[i]:]
        if eos_id is not None and eos_id in tail:
            row = row[:lens[i] + tail.index(eos_id) + 1]
        out_rows.append(row)
    return out_rows


class _Request:
    def __init__(self, prompt, num_tokens, eos_id, sampling=None):
        self.prompt = prompt
        self.num_tokens = num_tokens
        self.eos_id = eos_id
        #: None (greedy) or {"temperature", "top_k", "top_p", "seed"}
        self.sampling = sampling
        self.event = threading.Event()
        self.result: list[int] | None = None
        self.error: str | None = None
        self.abandoned = False   # caller timed out; don't decode for it

    @property
    def group_key(self):
        """Requests sharing a device call: same eos semantics, and —
        for sampled requests — the same seed (the seed is a scalar
        input; per-row temperature/top-k/top-p mix freely)."""
        return (self.eos_id,
                self.sampling.get("seed", 0) if self.sampling else None)


class Batcher:
    """Gather concurrent /generate requests into one device call.

    Blocks for the first request, then keeps gathering until ``max_batch``
    or ``wait_ms`` elapses — the standard latency/throughput knob.  Mixed
    eos_ids split into sub-batches (the mask semantics differ per id).

    ``decode_fn(prompts, num_tokens, eos_id) -> rows`` is whichever decode
    path the artifact supports (KV-cached pair or forward fallback).
    """

    def __init__(self, decode_fn, max_batch: int = 8,
                 wait_ms: float = 5.0, request_timeout_s: float = 60.0):
        self._decode_fn = decode_fn
        self._max_batch = max_batch
        self._wait_s = wait_ms / 1e3
        self.request_timeout_s = request_timeout_s
        self._q: queue.Queue[_Request] = queue.Queue()
        self.batch_sizes: list[int] = []   # served batch sizes (stats)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, prompt, num_tokens, eos_id, sampling=None):
        req = _Request(prompt, num_tokens, eos_id, sampling)
        self._q.put(req)
        if not req.event.wait(self.request_timeout_s):
            req.abandoned = True  # server overloaded: don't decode for us
            raise TimeoutError(
                f"decode queue exceeded {self.request_timeout_s:.0f}s")
        if req.error:
            raise ValueError(req.error)
        return req.result

    def _loop(self):
        while True:
            batch = [self._q.get()]
            deadline = time.monotonic() + self._wait_s
            while len(batch) < self._max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            batch = [r for r in batch if not r.abandoned]
            for key in {r.group_key for r in batch}:
                group = [r for r in batch if r.group_key == key]
                self._serve(group, key[0])

    def _serve(self, group, eos):
        self.batch_sizes.append(len(group))
        sampling = None
        if group[0].sampling is not None:
            # One seed per group (the group key); per-row configs.
            sampling = {
                "temperature": [r.sampling["temperature"] for r in group],
                "top_k": [r.sampling["top_k"] for r in group],
                "top_p": [r.sampling["top_p"] for r in group],
                "seed": group[0].sampling["seed"],
            }
        try:
            outs = self._decode_fn([r.prompt for r in group],
                                   [r.num_tokens for r in group], eos,
                                   sampling)
            for r, o in zip(group, outs):
                r.result = o
        except Exception as e:                     # surface to every caller
            for r in group:
                r.error = f"{type(e).__name__}: {e}"
        for r in group:
            r.event.set()


def make_server(artifact: str, port: int = 8600, max_batch: int = 8,
                wait_ms: float = 5.0,
                request_timeout_s: float = 60.0) -> ThreadingHTTPServer:
    """Build (not start) the HTTP server; ``.serve_forever()`` to run.
    Exposed separately so tests can drive it in-process."""
    exported, meta, cached = load_artifact(artifact)
    call = exported.call
    is_lm = meta.get("model") == "gpt_mini"
    seq_len = None
    if is_lm:
        seq_len = int(meta["inputs"][0]["shape"][-1])
        if cached is not None:
            def decode_fn(prompts, wants, eos, sampling=None, _c=cached,
                          _mb=max_batch):
                return decode_batch_cached(_c, prompts, wants, eos_id=eos,
                                           pad_batch=_mb,
                                           sampling=sampling)
        else:
            def decode_fn(prompts, wants, eos, sampling=None, _call=call,
                          _s=seq_len):
                if sampling is not None:
                    raise ValueError(
                        "sampling needs the KV-cached decode set; this "
                        "artifact serves the greedy forward fallback only")
                return decode_batch(_call, prompts, wants, _s, eos_id=eos)
        batcher = Batcher(decode_fn, max_batch=max_batch,
                          wait_ms=wait_ms,
                          request_timeout_s=request_timeout_s)
        meta = dict(meta,
                    serving_decode_path=("kv_cache" if cached is not None
                                         else "forward"))
    else:
        batcher = None

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive: every reply carries Content-Length, so the
        # connection survives across requests — a real slice of the r4
        # serving overhead was per-request TCP setup/teardown.
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):               # quiet server
            pass

        def _reply(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, {"status": "ok", **meta})
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except ValueError:
                return self._reply(400, {"error": "bad json"})
            try:
                if self.path == "/generate":
                    if batcher is None:
                        return self._reply(
                            400, {"error": f"artifact serves "
                                           f"{meta.get('model')}, not an "
                                           "LM; use /predict"})
                    sampling = None
                    temp = float(body.get("temperature", 0.0))
                    if temp > 0.0:
                        sampling = {
                            "temperature": temp,
                            "top_k": int(body.get("top_k", 0)),
                            "top_p": float(body.get("top_p", 0.0)),
                            "seed": int(body.get("seed", 0)),
                        }
                        if not 0.0 <= sampling["top_p"] <= 1.0:
                            return self._reply(
                                400, {"error": "top_p must be in [0, 1]"})
                    elif any(k in body for k in ("top_k", "top_p", "seed")):
                        # Don't silently decode greedily when the caller
                        # clearly asked for sampling.
                        return self._reply(
                            400, {"error": "top_k/top_p/seed require "
                                           "temperature > 0"})
                    toks = batcher.submit(
                        [int(t) for t in body["prompt"]],
                        int(body.get("num_tokens", 16)),
                        (int(body["eos_id"]) if "eos_id" in body else None),
                        sampling)
                    return self._reply(200, {"tokens": toks})
                if self.path == "/predict":
                    args = [np.asarray(a, dtype=s["dtype"]) for a, s in
                            zip([body["inputs"]] + body.get("extra", []),
                                meta["inputs"])]
                    out = np.asarray(call(*args))
                    return self._reply(200, {"outputs": out.tolist()})
                return self._reply(404, {"error": "unknown path"})
            except (KeyError, TypeError):
                return self._reply(400, {"error": "malformed request"})
            except TimeoutError as e:
                # Overload, not a caller mistake.
                return self._reply(503, {"error": str(e)})
            except ValueError as e:
                return self._reply(400, {"error": str(e)})

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    server.batcher = batcher                       # test/observability hook
    server.meta = meta
    return server


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--artifact", required=True)
    parser.add_argument("--port", type=int, default=8600)
    parser.add_argument("--max_batch", type=int, default=8)
    parser.add_argument("--batch_wait_ms", type=float, default=5.0)
    parser.add_argument("--request_timeout_s", type=float, default=60.0,
                        help="503 a /generate caller whose request waits "
                             "longer than this (overload signal)")
    parser.add_argument("--platform", default="",
                        help="jax platform override (e.g. cpu)")
    args = parser.parse_args(argv)
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    server = make_server(args.artifact, port=args.port,
                         max_batch=args.max_batch,
                         wait_ms=args.batch_wait_ms,
                         request_timeout_s=args.request_timeout_s)
    model = server.meta.get("model")
    path_note = server.meta.get("serving_decode_path")
    print(f"serving {model} from {args.artifact} "
          f"on :{server.server_address[1]} "
          f"(micro-batch up to {args.max_batch}, {args.batch_wait_ms}ms "
          "gather window"
          + (f", decode path: {path_note}" if path_note else "") + ")")
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
